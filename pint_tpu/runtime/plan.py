"""Execution-plan layer: route work onto the preflight-certified mesh.

ROADMAP item 1 — nothing in the library *routed* real work through a
mesh until this module.  An :class:`ExecutionPlan` is the single object
that answers "which devices, in what mesh shape, through which JAX
partitioning mechanism" for the three parallel axes of the framework:

* ``grid``   — the batch of grid points / parameter vectors (the
  reference's process-pool axis);
* ``toa``    — the data axis the GLS normal-equation contractions
  reduce over (cross-device all-reduces);
* ``walker`` — the MCMC ensemble axis.

Plan selection (:func:`select_plan`) starts from the per-device
preflight probes (:func:`pint_tpu.runtime.preflight.healthy_devices` —
a chip that fails the two_sum f64 probe never joins a mesh) and picks
the mechanism per workload:

* ``pjit``      — ``jax.jit`` + ``NamedSharding``/``PartitionSpec``
  when operand shardings are known (grid chunks, TOA-sharded normal
  equations); reductions become XLA SPMD collectives;
* ``shard_map`` — the pure data-parallel fallback (MCMC walkers): each
  device runs the batched function on its slice, with no cross-item
  reduction and therefore no accidental resharding collectives;
* ``single``    — the last rung of the ladder: one device, no mesh.

The device count is always a rung of the :func:`ladder` (descending
powers of two, 8→4→2→1) so the elastic supervisor
(:mod:`pint_tpu.runtime.elastic`) can degrade a plan one rung at a time
after evicting a sick device.  Every selection emits a ``plan_selected``
telemetry event; eviction/degradation events are the supervisor's.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional, Sequence, Tuple

import numpy as np

from pint_tpu import config
from pint_tpu.exceptions import MeshExhaustedError, UsageError
from pint_tpu.logging import log

__all__ = ["ExecutionPlan", "select_plan", "ladder", "MESH_AXES"]

#: the framework's parallel axes (DESIGN.md "Parallelism"); ``pulsar``
#: is the catalog engine's embarrassingly parallel batch axis — the
#: honest multichip route (no cross-device reduction exists to pay for)
MESH_AXES = ("grid", "toa", "walker", "pulsar")

#: workload -> (primary batch axis, multi-device mechanism)
_WORKLOAD_AXIS = {
    "grid": ("grid", "pjit"),
    "gls_normal_eq": ("toa", "pjit"),
    "walker": ("walker", "shard_map"),
    # batched catalog fits + the joint lnlikelihood: the bucket batch
    # axis shards over 'pulsar'; a 2-axis ('pulsar', 'walker') plan
    # adds walker-data-parallel sampling on the same mesh
    "catalog": ("pulsar", "pjit"),
}


def ladder(n: int) -> Tuple[int, ...]:
    """Degradation rungs available with ``n`` devices: descending powers
    of two ≤ n, ending at 1 (``ladder(8) == (8, 4, 2, 1)``; a 7-device
    survivor set yields ``(4, 2, 1)`` — mesh shapes stay power-of-two so
    chunk tiling and collective replica groups stay regular)."""
    if n < 1:
        raise MeshExhaustedError(
            f"no devices left to build a mesh from (n={n})")
    rungs = []
    r = 1 << (int(n).bit_length() - 1)
    while r >= 1:
        rungs.append(r)
        r //= 2
    return tuple(rungs)


def _emit_event(name: str, **attrs) -> None:
    """Elastic-lifecycle telemetry (also imported by runtime/elastic):
    the shared :func:`pint_tpu.telemetry.lifecycle_event` emitter —
    span event + full-mode runlog record."""
    if config._telemetry_mode == "off":
        return
    from pint_tpu import telemetry

    telemetry.lifecycle_event(name, **attrs)


@dataclass(frozen=True)
class ExecutionPlan:
    """One routed execution recipe: devices + mesh shape + mechanism.

    Frozen: the elastic supervisor never mutates a plan, it derives the
    next rung via :meth:`degraded` (so telemetry events can reference
    both the old and the new plan unambiguously)."""

    workload: str               #: "grid" | "gls_normal_eq" | "walker" | ...
    kind: str                   #: "pjit" | "shard_map" | "single"
    axes: Tuple[str, ...]       #: mesh axis names; axes[0] = batch axis
    devices: Tuple              #: healthy member devices (superset of mesh)
    rung: int                   #: devices actually meshed (a ladder rung)
    evicted: Tuple[int, ...] = ()   #: device ids removed by the supervisor
    _cache: dict = field(default_factory=dict, repr=False, compare=False)

    @property
    def mesh(self):
        """The ``jax.sharding.Mesh`` of this rung (None for single).
        Two-axis plans split the leading axis by 2 when the rung is even
        (the multichip dryrun's ``(grid, toa)`` layout)."""
        if self.rung <= 1:
            return None
        if "mesh" not in self._cache:
            from jax.sharding import Mesh

            devs = np.array(self.devices[: self.rung])
            if len(self.axes) == 1:
                self._cache["mesh"] = Mesh(devs, self.axes)
            else:
                lead = 2 if self.rung % 2 == 0 else 1
                self._cache["mesh"] = Mesh(
                    devs.reshape(lead, self.rung // lead), self.axes)
        return self._cache["mesh"]

    def batch_sharding(self):
        """``NamedSharding`` partitioning the batch (first) axis over
        ``axes[0]``, or None for a single-device plan."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axes[0]))

    def shard_map_batch(self, fn, out_axis0: bool = True):
        """Wrap a batched jax-traceable ``fn(batch) -> per-item out`` for
        pure data-parallel execution: each device runs ``fn`` on its
        batch slice (no collectives can appear — the shard_map contract).
        The batch length must be a multiple of the rung.  The wrapper's
        input buffer is donated: the batch is iteration state rebuilt
        every call (walker proposals), so XLA may reuse it in place."""
        if self.mesh is None:
            return fn
        key = ("shard_map", id(fn))
        if key not in self._cache:
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            axis = self.axes[0]
            inner = shard_map(fn, mesh=self.mesh,
                              in_specs=P(axis),
                              out_specs=P(axis) if out_axis0 else P(),
                              check_rep=False)
            self._cache[key] = jax.jit(inner, donate_argnums=(0,))
        return self._cache[key]

    def degraded(self, evict_ids: Sequence[int] = ()) -> "ExecutionPlan":
        """The next rung down, with ``evict_ids`` removed from
        membership.  Strictly descends the ladder even when no device
        was identified (collective timeout: SOME chip is sick, we just
        don't know which).  Raises :class:`MeshExhaustedError` below
        rung 1."""
        evict = set(int(i) for i in evict_ids)
        remaining = tuple(d for d in self.devices if d.id not in evict)
        if not remaining:
            raise MeshExhaustedError(
                "every device has been evicted; no rung remains")
        rungs = ladder(len(remaining))
        down = [r for r in rungs if r < self.rung]
        if not down:
            raise MeshExhaustedError(
                f"cannot degrade below rung {self.rung} "
                f"({len(remaining)} device(s) remain)")
        new_rung = down[0]
        return replace(
            self, devices=remaining, rung=new_rung,
            kind=self.kind if new_rung > 1 else "single",
            evicted=self.evicted + tuple(sorted(evict)),
            _cache={})

    @property
    def device_ids(self) -> Tuple[int, ...]:
        return tuple(int(d.id) for d in self.devices[: self.rung])

    def to_dict(self) -> dict:
        return {
            "workload": self.workload,
            "kind": self.kind,
            "axes": list(self.axes),
            "rung": int(self.rung),
            "n_devices": len(self.devices),
            "device_ids": list(self.device_ids),
            "evicted": list(self.evicted),
            "platform": str(self.devices[0].platform) if self.devices
            else None,
        }


#: batch (embarrassingly parallel) mesh axes: sharding one of these
#: moves NOTHING between devices — the data-parallel-first ranking
#: prefers them over reduction axes whenever the workload has a batch
_BATCH_AXES = ("pulsar", "walker", "grid")


def select_plan(workload: str = "grid",
                devices: Optional[Sequence] = None,
                n_items: Optional[int] = None,
                max_devices: Optional[int] = None,
                axes: Optional[Sequence[str]] = None,
                kind: Optional[str] = None,
                n_batch: Optional[int] = None) -> ExecutionPlan:
    """Auto-select the execution plan for ``workload`` from the
    preflight-certified device set.

    ``devices`` defaults to :func:`preflight.healthy_devices` — a chip
    that fails its per-device two_sum probe never joins the mesh.
    ``n_items`` caps the rung at the batch size (meshing 8 devices for
    3 points buys nothing), ``max_devices`` caps it absolutely, and
    ``kind`` forces the mechanism (tests / explicit shard_map opt-in).
    With ``axes`` unspecified the selection consults, in order: the
    autotuner's plan-strategy tunable (:func:`pint_tpu.autotune.
    resolve_plan_strategy` — cost-ranked by measured collective bytes,
    measure-confirmed; may override axes AND kind), then the
    data-parallel-first static rule below, then the tuned axis order
    (:func:`pint_tpu.autotune.resolve_plan_axes`).

    ``n_batch`` is the data-parallel-first hook (ROADMAP item 2): a
    caller holding ``n_batch`` independent fit systems that would
    otherwise TOA-shard each one (workload ``gls_normal_eq``) gets a
    ``pulsar``-axis data-parallel plan instead — the per-item Gram
    reduction moves K^2/D bytes per collective while the batch axis
    moves zero, so a batch of even two items out-ranks the sharded
    single fit.  Emits ``plan_strategy`` + ``plan_selected`` telemetry
    events.
    """
    from pint_tpu.runtime.preflight import healthy_devices

    if devices is None:
        devices = healthy_devices()
    devices = tuple(devices)
    if not devices:
        raise MeshExhaustedError(
            "no healthy devices: every per-device preflight probe failed")
    if workload not in _WORKLOAD_AXIS:
        raise UsageError(f"unknown workload {workload!r}; the routed "
                         f"workloads are {tuple(_WORKLOAD_AXIS)}")
    axis, default_kind = _WORKLOAD_AXIS[workload]
    if not axes:
        from pint_tpu import autotune as _autotune

        strategy = _autotune.resolve_plan_strategy(workload)
        if strategy is not None:
            tuned_axes = tuple(strategy.get("axes") or ())
            # a batch-axis strategy (the dataparallel winner) only
            # applies when the caller actually HAS a batch: a tuned
            # 'pulsar' plan handed to a single-fit caller would just
            # relabel its TOA sharding as data-parallelism
            if tuned_axes and tuned_axes[0] in _BATCH_AXES \
                    and axis not in _BATCH_AXES \
                    and (n_batch is None or int(n_batch) < 2):
                tuned_axes = ()
            if tuned_axes:
                axes = tuned_axes
                kind = kind or strategy.get("kind")
                _emit_event("plan_strategy", workload=workload,
                            chosen=",".join(axes), source="tuned")
        if not axes and n_batch is not None and int(n_batch) >= 2 \
                and axis not in _BATCH_AXES:
            # static data-parallel-first ranking: the batch axis moves
            # nothing; the reduction axis moves the Gram every solve
            axes = (_BATCH_AXES[0],)
            if n_items is None:
                n_items = int(n_batch)
            _emit_event("plan_strategy", workload=workload,
                        chosen=axes[0], source="static",
                        n_batch=int(n_batch))
        if not axes:
            axes = _autotune.resolve_plan_axes(workload)
    axes = tuple(axes) if axes else (axis,)
    for a in axes:
        if a not in MESH_AXES:
            raise UsageError(f"unknown mesh axis {a!r}; the framework's "
                             f"axes are {MESH_AXES}")
    n = len(devices)
    if max_devices is not None:
        n = min(n, int(max_devices))
    if n_items is not None:
        n = min(n, max(1, int(n_items)))
    rung = ladder(n)[0]
    resolved = kind or default_kind
    if rung == 1:
        resolved = "single"
    elif resolved not in ("pjit", "shard_map"):
        raise UsageError(f"unknown plan kind {resolved!r} "
                         "(pjit | shard_map | single)")
    plan = ExecutionPlan(workload=workload, kind=resolved, axes=axes,
                         devices=devices, rung=rung)
    log.info(f"execution plan: {workload} -> {resolved} on rung {rung} "
             f"({len(devices)} healthy device(s), axes {axes})")
    _emit_event("plan_selected", workload=workload, kind=resolved,
                rung=int(rung), n_devices=len(devices),
                axes=",".join(axes), device_ids=list(plan.device_ids))
    return plan
