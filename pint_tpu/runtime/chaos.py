"""Seeded chaos drills: the fault taxonomy injected under live load.

Every fault generator in :mod:`~pint_tpu.runtime.faultinject` has a
guardrail test — but each one fires against a single call, never
against a :class:`~pint_tpu.serving.service.TimingService` with
coalescing windows, admission control, circuit breakers, and open-loop
traffic all in flight at once.  This module is that drill: scripted
scenarios injected at the service's dispatch seam while a seeded
:class:`~pint_tpu.serving.loadgen.LoadGenerator` drives open-loop
load, asserting the **drill contract**:

1. every admitted request resolves — a result, a typed
   :class:`~pint_tpu.serving.admission.ShedResponse`, or (for the
   coalesced batch-mates of a fault-injected dispatch, before the
   breaker opens) the dispatch's exception.  ZERO stranded futures;
2. untyped failure stays bounded: once the door's circuit breaker
   opens, submits resolve as ``ShedResponse(reason="circuit_open")``
   data, so at most ``failures x quantum`` awaiters ever see the raw
   exception;
3. the service returns to steady state after the fault clears (the
   breaker's half-open probe closes it), with the recovery time
   measured;
4. post-drill results still match a dedicated dense solve at 1e-9 —
   the drill degraded availability, never correctness.

Scenarios (:data:`SCENARIOS`) cover the taxonomy end-to-end: device
loss mid-dispatch, a silently NaN-poisoning shard, a straggling
dispatch, an XLA-shaped collective failure, a corrupted/cold AOT
cache, a ``SimulatedCrash`` mid-coalesce, and a quarantine/release
storm on the update door.  The torn-journal-tail and
crash-at-every-op drills live with the recovery tests and the bench's
``recovery{}`` block, composed from the same seams
(:func:`~pint_tpu.runtime.faultinject.torn_tail` /
``crash_at_op`` + :meth:`~pint_tpu.serving.service.TimingService.
recover`).

Each drill emits one schema-tagged ``chaos_drill`` telemetry event and
returns a :class:`DrillReport`.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from pint_tpu import config
from pint_tpu.exceptions import UsageError
from pint_tpu.runtime.faultinject import (
    SimulatedCrash,
    SimulatedDeviceLoss,
)

__all__ = ["SCENARIOS", "DrillReport", "door_fault", "scenario_context",
           "run_drill", "storm_update_factory", "dedicated_fit"]

#: the scripted scenario registry: name -> what the fault models
SCENARIOS = {
    "device_loss": "the fit door's first k dispatches raise "
                   "SimulatedDeviceLoss (a flaky accelerator tunnel)",
    "nan_shard": "the first k dispatches return NaN-poisoned results "
                 "(a silently corrupting chip)",
    "straggler": "the first k dispatches stall (a wedged chip / "
                 "stuck collective) so deadline budgets must fire",
    "failed_collective": "the first k dispatches die with an "
                         "XLA-shaped collective RuntimeError",
    "corrupt_aot": "every warm-pool lookup misses (a corrupted AOT "
                   "blob store falls back to fresh compiles)",
    "crash_mid_coalesce": "the first k dispatches raise "
                          "SimulatedCrash with coalesced batches in "
                          "flight",
    "quarantine_storm": "an update-heavy mix hammers the stream with "
                        "alternating quarantine/release row ops",
}

#: post-drill correctness bar: served results vs a dedicated dense
#: solve (the acceptance criterion's 1e-9)
SPOT_CHECK_RTOL = 1e-9


def _emit_event(name: str, **attrs) -> None:
    """Drill-lifecycle telemetry: the shared
    :func:`pint_tpu.telemetry.lifecycle_event` emitter."""
    if config._telemetry_mode == "off":
        return
    from pint_tpu import telemetry

    telemetry.lifecycle_event(name, **attrs)


# ---------------------------------------------------------------------------
# the dispatch-seam fault (the service-level twin of faultinject)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def door_fault(service, mode: str, times: int = 3,
               delay_s: float = 0.0,
               exc_factory: Optional[Callable] = None):
    """Inject one failure mode into the fit door's dispatch for its
    first ``times`` coalesced batches: ``raise`` (exc_factory()),
    ``delay`` (sleep ``delay_s`` then dispatch), or ``nan`` (dispatch,
    then NaN-poison every result).  Plain attribute patching with
    restore-on-exit — the faultinject discipline at the
    ``batcher.run`` seam every async fit flush crosses."""
    if mode not in ("raise", "delay", "nan"):
        raise UsageError(f"door_fault mode {mode!r} not in "
                         "('raise', 'delay', 'nan')")
    orig = service.batcher.run
    state = {"calls": 0}
    make = exc_factory or (lambda: SimulatedDeviceLoss(
        "injected: device lost mid-dispatch"))

    def faulted(requests):
        if state["calls"] < times:
            state["calls"] += 1
            if mode == "raise":
                raise make()  # jaxlint: disable=typed-raise -- factory parameter; defaults to a typed SimulatedDeviceLoss
            if mode == "delay":
                time.sleep(delay_s)
                return orig(requests)
            results = orig(requests)
            for res in results:
                res.dx = np.full_like(res.dx, np.nan)
                res.errors = np.full_like(res.errors, np.nan)
                res.chi2 = float("nan")
            return results
        return orig(requests)

    service.batcher.run = faulted
    try:
        yield state
    finally:
        service.batcher.run = orig


@contextlib.contextmanager
def _cold_pool(service):
    """Every warm-pool lookup misses for the duration — the observable
    behavior of a corrupted AOT blob store (the loader drops a bad
    blob and recompiles; correctness survives, compiles spike)."""
    orig = service.pool.lookup

    def miss(name, args):
        return None

    service.pool.lookup = miss
    try:
        yield
    finally:
        service.pool.lookup = orig


def scenario_context(service, scenario: str, times: int = 3,
                     delay_s: float = 0.3):
    """The fault context manager for one named scenario (typed
    refusal on an unknown name).  ``quarantine_storm`` is a traffic
    shape, not a dispatch fault — its context is a no-op and the storm
    rides in the drill's update-heavy mix."""
    if scenario not in SCENARIOS:
        raise UsageError(
            f"unknown chaos scenario {scenario!r}; the registry has "
            f"{sorted(SCENARIOS)}")
    if scenario == "device_loss":
        return door_fault(service, "raise", times=times)
    if scenario == "nan_shard":
        return door_fault(service, "nan", times=times)
    if scenario == "straggler":
        return door_fault(service, "delay", times=times,
                          delay_s=delay_s)
    if scenario == "failed_collective":
        return door_fault(
            service, "raise", times=times,
            exc_factory=lambda: RuntimeError(  # jaxlint: disable=typed-raise -- XLA-shaped wording, the collective classifier's input
                "injected: all-reduce collective failed mid-dispatch"))
    if scenario == "crash_mid_coalesce":
        return door_fault(
            service, "raise", times=times,
            exc_factory=lambda: SimulatedCrash(  # jaxlint: disable=typed-raise -- a simulated host death must evade typed handling
                "injected: host died mid-coalesce"))
    if scenario == "corrupt_aot":
        return _cold_pool(service)
    return contextlib.nullcontext({})


def storm_update_factory(engine, block_id: Optional[int] = None,
                         rows=(0,)) -> Callable:
    """A zero-arg :class:`~pint_tpu.streaming.door.UpdateRequest`
    factory alternating quarantine/release of the same rows — the
    quarantine-storm traffic shape.  Alternation keeps every batch
    valid under the door's simulated-alive pre-validation whatever the
    coalescing cuts (a row is never quarantined twice without a
    release between)."""
    from pint_tpu.streaming.door import UpdateRequest

    if block_id is None:
        if not engine.cache.blocks:
            raise UsageError(
                "storm_update_factory needs a stream with >= 1 "
                "ingested block (or an explicit block_id)")
        block_id = int(engine.cache.blocks[0].block_id)
    rows = [int(r) for r in rows]
    state = {"n": 0}

    def factory():
        kind = "quarantine" if state["n"] % 2 == 0 else "release"
        state["n"] += 1
        return UpdateRequest(kind=kind, block_id=block_id, rows=rows,
                             request_id=f"storm-{state['n'] - 1}")

    return factory


# ---------------------------------------------------------------------------
# the drill
# ---------------------------------------------------------------------------

def dedicated_fit(req) -> np.ndarray:
    """The dedicated reference for one fit request: a dense
    prior-augmented normal-equation solve in plain numpy — no
    batching, no padding, no warm pool — the independent answer the
    drill contract's 1e-9 spot-check compares against."""
    A = req.M.T @ (req.w[:, None] * req.M) + np.diag(req.phiinv)
    b = req.M.T @ (req.w * req.r)
    return np.linalg.solve(A, b)


@dataclass
class DrillReport:
    """One chaos drill's outcome against the drill contract."""

    scenario: str
    offered: int = 0
    completed: int = 0
    shed: int = 0
    errored: int = 0
    stranded: int = 0
    duration_s: float = 0.0
    #: seconds from fault-clear to the first fully clean probe pass
    #: (None: the service never returned to steady state)
    recovery_s: Optional[float] = None
    #: worst relative error of the post-drill spot-check
    spot_check_rel_err: float = 0.0
    #: per-door breaker state after the drill
    breakers: Dict[str, dict] = field(default_factory=dict)
    #: untyped-failure budget the drill graded ``errored`` against
    errors_bound: int = 0
    #: postmortem bundles the flight recorder dumped during the drill
    postmortems: int = 0
    #: every drill must leave >= 1 bundle and every bundle must pass
    #: :func:`pint_tpu.telemetry.flightrec.validate_bundle`
    postmortem_ok: bool = False
    #: contract violations, empty when the drill passed
    violations: List[str] = field(default_factory=list)
    per_class: Dict[str, dict] = field(default_factory=dict)

    @property
    def contract_ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {"scenario": self.scenario, "offered": self.offered,
                "completed": self.completed, "shed": self.shed,
                "errored": self.errored, "stranded": self.stranded,
                "duration_s": self.duration_s,
                "recovery_s": self.recovery_s,
                "spot_check_rel_err": self.spot_check_rel_err,
                "errors_bound": self.errors_bound,
                "postmortems": self.postmortems,
                "postmortem_ok": self.postmortem_ok,
                "breakers": self.breakers,
                "contract_ok": self.contract_ok,
                "violations": list(self.violations),
                "per_class": self.per_class}


def _steady_probe(service, shapes, mix, update_factory, seed: int,
                  n: int = 4):
    """One small closed-loop pass: fully clean (everything completed,
    nothing shed or errored) means the service is back in steady
    state."""
    from pint_tpu.serving.loadgen import LoadConfig, LoadGenerator

    cfg = LoadConfig(arrival="closed", concurrency=1, n_requests=n,
                     mix=mix, seed=seed, tolerate_errors=True)
    rep = LoadGenerator(service, cfg, shapes=shapes,
                        update_factory=update_factory).run()
    return rep.completed == rep.offered


def run_drill(service, scenario: str, rps: float = 400.0,
              n_requests: int = 64, times: int = 3,
              delay_s: float = 0.3, seed: int = 0,
              shapes=None, update_factory: Optional[Callable] = None,
              spot_checks: int = 3,
              recovery_timeout_s: float = 20.0,
              drill_timeout_s: float = 120.0) -> DrillReport:
    """Run one scripted chaos scenario against a LIVE service under
    seeded open-loop load and grade the drill contract (module
    docstring).  Returns the :class:`DrillReport`; the caller (test,
    bench) asserts on ``contract_ok`` / ``violations``.

    The service should be configured with a drill-friendly breaker
    (small ``reset_s``) so recovery is measurable inside
    ``recovery_timeout_s``."""
    import asyncio

    from pint_tpu.serving.loadgen import (
        LoadConfig,
        LoadGenerator,
        ShapePopulation,
    )

    if scenario not in SCENARIOS:
        raise UsageError(
            f"unknown chaos scenario {scenario!r}; the registry has "
            f"{sorted(SCENARIOS)}")
    shapes = shapes or ShapePopulation.synthetic(n=4, seed=seed)
    if scenario == "quarantine_storm":
        if update_factory is None:
            update_factory = storm_update_factory(
                service._require_stream())
        mix = {"update": 3.0, "fit": 1.0}
    else:
        mix = {"fit": 1.0}
    cfg = LoadConfig(arrival="open", rps=rps, n_requests=n_requests,
                     mix=mix, seed=seed, tolerate_errors=True)
    gen = LoadGenerator(service, cfg, shapes=shapes,
                        update_factory=update_factory)
    report = DrillReport(scenario=scenario)
    t0 = time.perf_counter()

    async def _drive():
        return await asyncio.wait_for(gen.run_async(),
                                      timeout=drill_timeout_s)

    timed_out = False
    dumps_before = service.flight_recorder.dumps
    with scenario_context(service, scenario, times=times,
                          delay_s=delay_s):
        # black-box capture at injection time: whatever the scenario
        # does (some never open a breaker), every drill leaves a
        # postmortem of the service state the fault landed on
        service.dump_postmortem(f"chaos drill injected: {scenario}")
        try:
            load = asyncio.run(_drive())
        except (TimeoutError, asyncio.TimeoutError):
            # a hung drill IS the stranded-future failure mode the
            # contract exists to catch
            timed_out = True
            load = None
    t_clear = time.perf_counter()
    report.duration_s = t_clear - t0
    if timed_out:
        report.stranded = -1
        report.violations.append(
            f"drill timed out after {drill_timeout_s}s — stranded "
            "futures (awaiters never resolved)")
    else:
        report.offered = load.offered
        report.completed = load.completed
        report.shed = load.shed
        report.errored = load.errored
        report.stranded = load.stranded
        report.per_class = load.per_class
        if load.stranded != 0:
            report.violations.append(
                f"{load.stranded} stranded future(s): offered "
                f"{load.offered} != completed {load.completed} + shed "
                f"{load.shed} + errored {load.errored}")
        # once the breaker opens, failure resolves as typed shed data;
        # only the coalesced riders of the first `failures` sick
        # dispatches (+ half-open probes) may see the raw exception
        quantum = service.scheduler.quantum("fit")
        brk = service._fit.breaker.cfg
        report.errors_bound = (brk.failures + max(0, times)) * quantum
        if report.errored > report.errors_bound:
            report.violations.append(
                f"untyped failure unbounded: {report.errored} errored "
                f"awaiters > bound {report.errors_bound} (breaker "
                "never contained the fault)")
        # recovery: fault cleared — probe until one fully clean pass
        while time.perf_counter() - t_clear < recovery_timeout_s:
            if _steady_probe(service, shapes, mix, update_factory,
                             seed=seed + 1):
                report.recovery_s = time.perf_counter() - t_clear
                break
            time.sleep(0.02)
        if report.recovery_s is None:
            report.violations.append(
                f"service did not return to steady state within "
                f"{recovery_timeout_s}s of the fault clearing")
        # post-drill correctness: served results vs the dedicated
        # dense solve — the drill degraded availability, never answers
        rel = 0.0
        for i in range(int(spot_checks)):
            req = gen._operands[i % len(shapes.shapes)]
            res = service.serve([req])[0]
            ref = dedicated_fit(req)
            rel = max(rel, float(
                np.max(np.abs(res.dx - ref)
                       / np.maximum(np.abs(ref), 1e-300))))
        report.spot_check_rel_err = rel
        if not np.isfinite(rel) or rel > SPOT_CHECK_RTOL:
            report.violations.append(
                f"post-drill spot-check diverged: rel err {rel:.3e} "
                f"> {SPOT_CHECK_RTOL:.0e} vs the dedicated solve")
    report.breakers = service.breakers()
    # postmortem contract: the drill must have produced >= 1 bundle
    # (injection capture + any breaker-open / dispatch-failure dumps)
    # and every retained bundle must validate against postmortem/1
    from pint_tpu.telemetry.flightrec import validate_bundle

    report.postmortems = service.flight_recorder.dumps - dumps_before
    bundle_errors: List[str] = []
    for bundle in service.flight_recorder.bundles:
        validate_bundle(bundle, where=f"drill:{scenario}",
                        errors=bundle_errors)
    report.postmortem_ok = report.postmortems >= 1 and not bundle_errors
    if report.postmortems < 1:
        report.violations.append(
            "drill produced no postmortem bundle (the flight recorder "
            "never dumped)")
    elif bundle_errors:
        report.violations.append(
            f"postmortem bundle(s) failed validation: "
            f"{'; '.join(bundle_errors[:3])}")
    _emit_event("chaos_drill", scenario=scenario,
                offered=int(report.offered),
                completed=int(report.completed),
                shed=int(report.shed),
                errored=int(report.errored),
                stranded=int(report.stranded),
                duration_s=float(report.duration_s),
                recovery_s=float(report.recovery_s
                                 if report.recovery_s is not None
                                 else -1.0),
                postmortems=int(report.postmortems),
                postmortem_ok=bool(report.postmortem_ok),
                contract_ok=bool(report.contract_ok))
    return report
