"""Elastic supervisor: fault-tolerant sharded sweeps that degrade, not die.

The paper's sub-nanosecond phase contract makes *silent shard
corruption worse than a crash*: a sick chip that keeps computing poisons
every surface it touches.  This module wraps the execution-plan layer
(:mod:`pint_tpu.runtime.plan`) in a supervisor that, on a collective
timeout, device loss, or per-attempt failure mid-sweep:

1. classifies the failure (:func:`classify_failure`) and, when a device
   is identified, **evicts** it from mesh membership;
2. rebuilds the mesh one rung down the 8→4→2→1 ladder
   (:meth:`ExecutionPlan.degraded`) and re-dispatches;
3. resumes from the last checkpoint — chunk boundaries are *logical*
   (device-count-independent), so a sweep checkpointed on 8 devices
   resumes on 4 with identical results; the mesh identity lives in the
   checkpoint's **sidecar** (:class:`~pint_tpu.runtime.checkpoint
   .SweepCheckpoint`), never in its fingerprint.

Silent corruption is caught by the **cross-replica canary**: every
dispatched block carries one replicated grid point at the head of each
device's shard.  Healthy devices run the same program on the same value
and must agree to fp noise; a NaN or off-median canary convicts its
shard (:class:`~pint_tpu.exceptions.CanaryMismatchError`) and the
device is evicted.

Telemetry: ``plan_selected`` (plan layer), ``device_evicted``,
``mesh_degraded``, and a final ``elastic.sweep_done`` carrying the
recompile accounting — one recompile per rung change is expected and
counted; *steady-state* recompiles after degradation settles must be
zero (the executable is keyed by block shape, which is constant per
rung).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from pint_tpu import config
from pint_tpu.exceptions import (
    CanaryMismatchError,
    DeviceLostError,
    MeshExhaustedError,
    SweepChunkFailure,
    UsageError,
)
from pint_tpu.logging import log
from pint_tpu.runtime import checkpoint as _cp
from pint_tpu.runtime.plan import ExecutionPlan, _emit_event, select_plan

__all__ = ["elastic_map", "ElasticReport", "classify_failure",
           "check_canary", "run_with_degradation"]

#: substrings that mark a runtime error as a failed/timed-out collective
#: (the XLA client's wording across backends)
_COLLECTIVE_MARKERS = ("collective", "all-reduce", "allreduce",
                       "all-gather", "reduce-scatter", "all-to-all",
                       "deadline", "timed out", "timeout")


def classify_failure(exc: BaseException) -> Optional[dict]:
    """``{"kind": ..., "devices": [ids]}`` for elastic-recoverable
    failures, None for everything else (which must propagate: a typed
    solve failure re-run on fewer devices would fail identically)."""
    if isinstance(exc, SweepChunkFailure):
        # retry-exhaustion wrapper (checkpoint.with_retries): classify
        # the underlying failure, so a wrapped device loss degrades and
        # a wrapped unclassifiable failure still propagates
        return classify_failure(exc.__cause__) if exc.__cause__ is not None \
            else None
    if isinstance(exc, CanaryMismatchError):
        return {"kind": "canary_mismatch",
                "devices": [d for d in exc.device_ids if d is not None]}
    if isinstance(exc, DeviceLostError):
        did = getattr(exc, "device_id", None)
        return {"kind": "device_loss",
                "devices": [did] if did is not None else []}
    if isinstance(exc, _cp._TIMEOUT_ERRORS):
        return {"kind": "collective_timeout", "devices": []}
    if type(exc).__name__ == "XlaRuntimeError" \
            or isinstance(exc, RuntimeError):
        msg = str(exc).lower()
        if any(m in msg for m in _COLLECTIVE_MARKERS):
            return {"kind": "collective_failure", "devices": []}
        if "device" in msg:
            return {"kind": "device_loss", "devices": []}
    return None


def check_canary(values, plan: ExecutionPlan, rtol: float = 1e-9,
                 where: str = "") -> None:
    """Cross-replica agreement check: ``values[d]`` is the canary result
    computed by device ``d`` of the plan's mesh.  All shards ran the
    same program on the same point, so healthy devices agree to fp
    noise; NaN or off-median values convict their device."""
    vals = np.asarray(values, dtype=np.float64)
    finite = np.isfinite(vals)
    if not finite.any():
        # every shard returned the same non-finite verdict: a NaN chi2
        # is a legitimate grid outcome (unsolvable point), and unanimous
        # agreement on it is agreement, not per-device corruption
        return
    ref = float(np.median(vals[finite]))
    bad = ~finite | (np.abs(vals - ref) > rtol * max(abs(ref), 1.0))
    if bad.any():
        ids = [int(plan.devices[i].id) for i in np.nonzero(bad)[0]
               if i < len(plan.devices)]
        raise CanaryMismatchError(
            f"cross-replica canary mismatch{' in ' + where if where else ''}"
            f": device(s) {ids} disagree (values {vals.tolist()}, "
            f"reference {ref!r}) — silent shard corruption",
            device_ids=ids)


@dataclass
class ElasticReport:
    """What the supervisor did: rungs visited, devices evicted, and the
    recompile accounting the acceptance gate asserts on."""

    rungs: List[int] = field(default_factory=list)
    evicted: List[int] = field(default_factory=list)
    degradations: int = 0
    chunks_resumed: int = 0
    chunks_computed: int = 0
    canary_checks: int = 0
    #: compiles observed on the FIRST dispatch at each rung (expected:
    #: one executable per rung change)
    recompiles_by_rung: Dict[int, int] = field(default_factory=dict)
    #: compiles observed on any LATER dispatch at an already-warm rung —
    #: must stay 0 once degradation settles
    steady_state_recompiles: int = 0
    final_plan: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "rungs": list(self.rungs),
            "evicted": list(self.evicted),
            "degradations": int(self.degradations),
            "chunks_resumed": int(self.chunks_resumed),
            "chunks_computed": int(self.chunks_computed),
            "canary_checks": int(self.canary_checks),
            "recompiles_by_rung": {str(k): int(v) for k, v in
                                   self.recompiles_by_rung.items()},
            "steady_state_recompiles": int(self.steady_state_recompiles),
            "final_plan": self.final_plan,
        }


#: indirection for the block dispatch so the fault-injection harness can
#: interpose shard-level faults (device loss at chunk k, NaN on one
#: shard, straggler delay, failed collective) without touching the
#: supervisor logic — the same seam discipline as checkpoint._invoke
def _invoke_block(eval_fn: Callable, block: np.ndarray, index: int,
                  plan: ExecutionPlan):
    return eval_fn(block)


#: the scan-fused sibling seam: ``blocks`` is the stacked (fuse, B, G)
#: group, ``group`` the logical chunk indices it retires — same
#: fault-injection discipline as _invoke_block
def _invoke_fused(eval_fn: Callable, blocks: np.ndarray, group,
                  plan: ExecutionPlan):
    return eval_fn(blocks)


def _block_layout(chunk: int, plan: ExecutionPlan,
                  canary: bool) -> Tuple[int, np.ndarray, np.ndarray]:
    """(block_size, canary_row_indices, real_row_indices) for one
    logical chunk of ``chunk`` points dispatched on ``plan``.

    Multi-device blocks interleave one canary row at the head of each
    device's shard: rung D, q = ceil(chunk/D) real rows per device,
    block = D*(q+1) rows.  Row layout per device d:
    ``[canary, pt[d*q], ..., pt[d*q+q-1]]`` — so the canary costs D rows
    out of the block, not a second dispatch."""
    D = plan.rung
    if D <= 1 or not canary:
        return chunk, np.empty(0, dtype=int), np.arange(chunk)
    q = -(-chunk // D)
    per = q + 1
    canary_rows = np.arange(D) * per
    real_rows = np.concatenate(
        [d * per + 1 + np.arange(q) for d in range(D)])[:chunk]
    return D * per, canary_rows, real_rows


def _degrade(plan: ExecutionPlan, info: dict, chunk_index: int,
             report: ElasticReport) -> ExecutionPlan:
    """Evict identified devices, drop one rung, emit the telemetry."""
    for did in info["devices"]:
        report.evicted.append(int(did))
        _emit_event("device_evicted", device_id=int(did),
                    reason=info["kind"], chunk=int(chunk_index))
        log.warning(f"elastic: evicting device {did} "
                    f"({info['kind']} at chunk {chunk_index})")
    new = plan.degraded(evict_ids=info["devices"])
    report.degradations += 1
    report.rungs.append(new.rung)
    _emit_event("mesh_degraded", from_rung=int(plan.rung),
                to_rung=int(new.rung), reason=info["kind"],
                chunk=int(chunk_index),
                n_remaining=len(new.devices))
    log.warning(f"elastic: mesh degraded {plan.rung} -> {new.rung} "
                f"device(s) ({info['kind']} at chunk {chunk_index}); "
                "resuming from last checkpoint")
    return new


def _compile_delta(before) -> int:
    """Backend compiles since ``before`` (None when accounting is off)."""
    if before is None:
        return 0
    from pint_tpu.telemetry import jaxevents

    return (jaxevents.counts() - before).compiles


def _compile_mark():
    if config._telemetry_mode == "off":
        return None
    from pint_tpu.telemetry import jaxevents

    jaxevents.install()
    return jaxevents.counts()


def elastic_map(make_eval: Callable[[int, ExecutionPlan], Callable],
                points: np.ndarray,
                *,
                plan: Optional[ExecutionPlan] = None,
                chunk: int = 128,
                checkpoint: Optional[str] = None,
                fingerprint: Optional[dict] = None,
                retry: Optional[_cp.RetryPolicy] = None,
                canary: bool = True,
                canary_key: str = "chi2",
                canary_rtol: float = 1e-9,
                what: str = "elastic sweep",
                fuse: int = 1,
                make_fused_eval: Optional[Callable] = None
                ) -> Tuple[Dict[str, np.ndarray], ElasticReport]:
    """Map a sharded evaluator over ``points`` with eviction/degradation.

    ``make_eval(block_size, plan)`` builds the evaluator for one rung:
    a callable ``(block (B, G) ndarray) -> {name: (B, ...) ndarray}``
    that dispatches the block through the plan's mesh.  It is invoked
    once per rung (the per-rung executable — exactly one recompile per
    rung change).

    ``fuse`` > 1 (with ``make_fused_eval(block_size, fuse, plan)``
    supplied — a callable returning ``(blocks (fuse, B, G)) -> {name:
    (fuse, B, ...)}``) dispatches groups of up to ``fuse`` consecutive
    logical chunks through ONE scan-fused executable per group (the
    work-per-byte dispatch amortization).  Checkpoint granularity STAYS
    logical: each chunk of a fused group persists individually, so a
    fused sweep resumes — including across mesh rungs after
    degradation — exactly like an unfused one; short groups pad by
    repeating the last block (one executable shape per rung).

    Chunk boundaries are **logical**: ``chunk`` points per chunk
    regardless of device count, every chunk padded to full size (the
    pad repeats the last point), so (a) checkpoints written at one rung
    resume at any other, and (b) block shapes are constant per rung and
    the steady state never recompiles.  With ``checkpoint`` set,
    completed chunks persist via :class:`SweepCheckpoint` with the
    current plan in the sidecar; ``fingerprint`` must therefore never
    include mesh identity.
    """
    policy = retry or _cp.RetryPolicy()
    points = np.asarray(points)
    npts = points.shape[0]
    if npts == 0:
        return {}, ElasticReport()
    if plan is None:
        plan = select_plan("grid", n_items=npts)
    if len(plan.axes) != 1:
        # the canary layout and check_canary's row->device attribution
        # assume one batch shard per mesh device; a multi-axis plan
        # replicates the batch over the trailing axes, so a conviction
        # would name devices that never computed the offending rows
        raise UsageError(
            f"elastic_map requires a single-axis plan (got axes "
            f"{plan.axes}); build one with select_plan(workload)")
    fuse = max(1, int(fuse))
    if fuse > 1 and make_fused_eval is None:
        raise UsageError("fuse > 1 needs make_fused_eval (the scan-fused "
                         "per-rung evaluator builder)")
    nchunks = -(-npts // chunk)
    report = ElasticReport(rungs=[plan.rung])

    ckpt = None
    if checkpoint is not None:
        fp = _cp.fingerprint_of(**(fingerprint or {}))
        ckpt = _cp.SweepCheckpoint(checkpoint, fp, nchunks,
                                   sidecar={"plan": plan.to_dict()})
        done = ckpt.completed()
        if done:
            log.info(f"{what}: resuming with {len(done)}/{nchunks} "
                     "chunks already complete")

    evals: Dict[int, Callable] = {}      # rung -> evaluator
    fused_evals: Dict[int, Callable] = {}  # rung -> scan-fused evaluator
    layouts: Dict[int, tuple] = {}       # rung -> (B, canary_rows, real_rows)
    warm_rungs: set = set()              # rungs whose first dispatch ran
    canary_pt = points[0]

    def _get_layout(p: ExecutionPlan) -> tuple:
        if p.rung not in layouts:
            layouts[p.rung] = _block_layout(chunk, p, canary)
        return layouts[p.rung]

    def _get_eval(p: ExecutionPlan) -> Tuple[Callable, tuple]:
        layout = _get_layout(p)
        if fuse > 1:
            # fused mode builds ONLY the scan-fused executable per rung
            # (a parallel unfused executable would double the compiles)
            if p.rung not in fused_evals:
                fused_evals[p.rung] = make_fused_eval(layout[0], fuse, p)
            return fused_evals[p.rung], layout
        if p.rung not in evals:
            evals[p.rung] = make_eval(layout[0], p)
        return evals[p.rung], layout

    def _assemble(chunk_pts: np.ndarray, layout) -> np.ndarray:
        B, canary_rows, real_rows = layout
        padded = chunk_pts
        if len(padded) < chunk:
            padded = np.concatenate(
                [padded, np.tile(padded[-1:], (chunk - len(padded), 1))])
        block = np.repeat(padded[-1:], B, axis=0)
        if len(canary_rows):
            block[canary_rows] = canary_pt
        block[real_rows] = padded
        return block

    out_chunks: List[Optional[dict]] = [None] * nchunks
    i = 0
    while i < nchunks:
        if ckpt is not None and ckpt.has(i):
            out_chunks[i] = ckpt.load(i)
            report.chunks_resumed += 1
            if config._telemetry_mode != "off":
                from pint_tpu import telemetry as _tel

                _tel.event("sweep.chunk_resumed", index=i)
            i += 1
            continue
        # the dispatch group: up to ``fuse`` consecutive chunks with no
        # checkpoint (a checkpointed chunk mid-run splits the group —
        # resumed work is never recomputed just to fill a scan)
        group = [i]
        while len(group) < fuse and group[-1] + 1 < nchunks \
                and not (ckpt is not None and ckpt.has(group[-1] + 1)):
            group.append(group[-1] + 1)

        attempt = 0
        # ONE same-rung retry for unattributed transients; after that a
        # repeat failure costs a rung (some chip is sick — keep sweeping)
        transient_left = 1
        while True:
            eval_fn, layout = _get_eval(plan)
            blocks = [_assemble(points[g * chunk:min((g + 1) * chunk,
                                                     npts)], layout)
                      for g in group]
            mark = _compile_mark()
            try:
                if fuse > 1:
                    stacked = np.stack(
                        blocks + [blocks[-1]] * (fuse - len(blocks)))
                    # the retry policy's timeout is PER CHUNK; a fused
                    # dispatch retires len(group) chunks of work, so a
                    # budget sized for one chunk must scale or healthy
                    # fused sweeps would time out into degradation
                    group_timeout = None if policy.timeout is None \
                        else policy.timeout * len(group)
                    outs = _cp._call_with_timeout(
                        lambda: _invoke_fused(eval_fn, stacked, group,
                                              plan),
                        group_timeout)
                    per_chunk = [{k: np.asarray(v)[f]
                                  for k, v in outs.items()}
                                 for f in range(len(group))]
                else:
                    out = _cp._call_with_timeout(
                        lambda: _invoke_block(eval_fn, blocks[0],
                                              group[0], plan),
                        policy.timeout)
                    per_chunk = [out]
                B, canary_rows, real_rows = layout
                if len(canary_rows):
                    for gi, out in zip(group, per_chunk):
                        report.canary_checks += 1
                        check_canary(
                            np.asarray(out[canary_key])[canary_rows],
                            plan, rtol=canary_rtol,
                            where=f"{what} chunk {gi}")
                compiles = _compile_delta(mark)
                if plan.rung in warm_rungs:
                    report.steady_state_recompiles += compiles
                else:
                    report.recompiles_by_rung[plan.rung] = compiles
                    warm_rungs.add(plan.rung)
                results = []
                for gi, out in zip(group, per_chunk):
                    lo, hi = gi * chunk, min((gi + 1) * chunk, npts)
                    results.append({k: np.asarray(v)[real_rows][: hi - lo]
                                    for k, v in out.items()})
                break
            except Exception as e:  # noqa: BLE001 — classified below
                info = classify_failure(e)
                if info is None:
                    raise
                attempt += 1
                log.warning(f"{what} chunk {group[0]}: {info['kind']} "
                            f"({type(e).__name__}: {e})")
                if not info["devices"] and transient_left > 0 \
                        and info["kind"] in ("collective_timeout",
                                             "collective_failure"):
                    # no device identified: one same-rung retry first —
                    # a transient tunnel hiccup shouldn't cost a rung
                    transient_left -= 1
                    delay = policy.backoff_base \
                        * policy.backoff_factor ** (attempt - 1)
                    if delay > 0:
                        time.sleep(delay)
                    continue
                try:
                    plan = _degrade(plan, info, group[0], report)
                except MeshExhaustedError as exhausted:
                    raise SweepChunkFailure(
                        f"{what} chunk {group[0]}: degradation ladder "
                        f"exhausted after {attempt} attempt(s) "
                        f"(last: {type(e).__name__}: {e})") from exhausted
                if ckpt is not None:
                    ckpt.update_sidecar({"plan": plan.to_dict()})

        for gi, res in zip(group, results):
            report.chunks_computed += 1
            if ckpt is not None:
                ckpt.save(gi, **res)
            if config._telemetry_mode != "off":
                from pint_tpu import telemetry as _tel

                _tel.event("sweep.chunk_done", index=gi, total=nchunks,
                           persisted=ckpt is not None)
            out_chunks[gi] = res
        i = group[-1] + 1

    report.final_plan = plan.to_dict()
    _emit_event("elastic.sweep_done", chunks=nchunks,
                rungs=[int(r) for r in report.rungs],
                evicted=[int(d) for d in report.evicted],
                degradations=int(report.degradations),
                steady_state_recompiles=int(report.steady_state_recompiles),
                recompiles_by_rung={str(k): int(v) for k, v in
                                    report.recompiles_by_rung.items()})
    keys = out_chunks[0].keys()
    merged = {k: np.concatenate([c[k] for c in out_chunks]) for k in keys}
    return merged, report


def run_with_degradation(plan: ExecutionPlan, fn: Callable,
                         what: str = "sharded evaluation",
                         max_transient: int = 1):
    """Run ``fn(plan)`` under elastic supervision: classified failures
    evict/degrade and re-run on the next rung; everything else
    propagates.  Returns ``(result, final_plan, report)`` — callers
    that hold a plan (sampler, GLS fitter) adopt the survivor and keep
    the eviction/degradation accounting.  The lightweight sibling of
    :func:`elastic_map` for non-chunked evaluations."""
    report = ElasticReport(rungs=[plan.rung])
    transient_left = max_transient
    while True:
        try:
            result = fn(plan)
            report.final_plan = plan.to_dict()
            return result, plan, report
        except Exception as e:  # noqa: BLE001 — classified below
            info = classify_failure(e)
            if info is None:
                raise
            log.warning(f"{what}: {info['kind']} "
                        f"({type(e).__name__}: {e})")
            if not info["devices"] and transient_left > 0 \
                    and info["kind"] in ("collective_timeout",
                                         "collective_failure"):
                transient_left -= 1
                continue
            try:
                plan = _degrade(plan, info, -1, report)
            except MeshExhaustedError as exhausted:
                raise SweepChunkFailure(
                    f"{what}: degradation ladder exhausted "
                    f"(last: {type(e).__name__}: {e})") from exhausted
