"""Runtime guardrail layer: device-health preflight, hardened solve
ladders, checkpointed sweeps, and fault injection.

Every fitting entry point in pint_tpu routes through one of three
guardrails so that bad inputs or flaky devices **fail loudly, degrade
gracefully, or recover** — never silently emit wrong numbers:

* :mod:`pint_tpu.runtime.preflight` — probe the platform that actually
  executes traces (and its f64-emulation regime, DESIGN.md) and attach a
  :class:`~pint_tpu.runtime.preflight.DeviceProfile` to fit results; a
  ``strict``/``warn``/``allow`` policy knob lives in
  :mod:`pint_tpu.config`.
* :mod:`pint_tpu.runtime.solve` — Cholesky -> jittered-Cholesky -> SVD
  escalation for every normal-equation solve, host-side (fitters) and
  on-trace (vmapped grid bodies), with per-solve diagnostics.
* :mod:`pint_tpu.runtime.checkpoint` — chunked sweep executor with
  per-chunk persistence, retry/backoff, timeout, and crash resume
  (mesh identity in the sidecar, never in the fingerprint — checkpoints
  are portable across device counts).
* :mod:`pint_tpu.runtime.plan` — execution-plan layer: mesh membership
  from the per-device preflight probes, pjit/shard_map/single mechanism
  selection per workload axis (grid/toa/walker).
* :mod:`pint_tpu.runtime.elastic` — elastic supervisor: cross-replica
  canary, device eviction, 8→4→2→1 mesh degradation, resume from the
  last checkpoint.
* :mod:`pint_tpu.runtime.faultinject` — deterministic fault injection
  (NaN residuals, singular Grams, truncated files, device loss,
  shard-level faults, torn/corrupt journal records) used by
  ``tests/test_fault_injection.py`` and ``tests/test_elastic.py`` to
  prove each guardrail fires.
* :mod:`pint_tpu.runtime.chaos` — seeded chaos drills: the scripted
  fault scenarios injected into a live
  :class:`~pint_tpu.serving.service.TimingService` under open-loop
  load, asserting the drill contract (zero stranded futures, typed
  sheds, bounded degradation, measured recovery to steady state).
"""

from pint_tpu.runtime.preflight import (  # noqa: F401
    DeviceHealth,
    DeviceProfile,
    check_device,
    device_health,
    device_profile,
    healthy_devices,
)
from pint_tpu.runtime.plan import (  # noqa: F401
    ExecutionPlan,
    ladder,
    select_plan,
)
from pint_tpu.runtime.elastic import (  # noqa: F401
    ElasticReport,
    elastic_map,
)
from pint_tpu.runtime.solve import (  # noqa: F401
    SolveDiagnostics,
    hardened_cholesky,
    ladder_cholesky_solve,
    solve_normal_cholesky,
)
from pint_tpu.runtime.checkpoint import (  # noqa: F401
    RetryPolicy,
    SweepCheckpoint,
    checkpointed_map,
    with_retries,
)

__all__ = [
    "DeviceProfile", "DeviceHealth", "device_profile", "device_health",
    "healthy_devices", "check_device",
    "SolveDiagnostics", "hardened_cholesky", "solve_normal_cholesky",
    "ladder_cholesky_solve",
    "RetryPolicy", "SweepCheckpoint", "checkpointed_map", "with_retries",
    "ExecutionPlan", "select_plan", "ladder",
    "ElasticReport", "elastic_map",
]
