"""Runtime guardrail layer: device-health preflight, hardened solve
ladders, checkpointed sweeps, and fault injection.

Every fitting entry point in pint_tpu routes through one of three
guardrails so that bad inputs or flaky devices **fail loudly, degrade
gracefully, or recover** — never silently emit wrong numbers:

* :mod:`pint_tpu.runtime.preflight` — probe the platform that actually
  executes traces (and its f64-emulation regime, DESIGN.md) and attach a
  :class:`~pint_tpu.runtime.preflight.DeviceProfile` to fit results; a
  ``strict``/``warn``/``allow`` policy knob lives in
  :mod:`pint_tpu.config`.
* :mod:`pint_tpu.runtime.solve` — Cholesky -> jittered-Cholesky -> SVD
  escalation for every normal-equation solve, host-side (fitters) and
  on-trace (vmapped grid bodies), with per-solve diagnostics.
* :mod:`pint_tpu.runtime.checkpoint` — chunked sweep executor with
  per-chunk persistence, retry/backoff, timeout, and crash resume.
* :mod:`pint_tpu.runtime.faultinject` — deterministic fault injection
  (NaN residuals, singular Grams, truncated files, device loss) used by
  ``tests/test_fault_injection.py`` to prove each guardrail fires.
"""

from pint_tpu.runtime.preflight import (  # noqa: F401
    DeviceProfile,
    check_device,
    device_profile,
)
from pint_tpu.runtime.solve import (  # noqa: F401
    SolveDiagnostics,
    hardened_cholesky,
    ladder_cholesky_solve,
    solve_normal_cholesky,
)
from pint_tpu.runtime.checkpoint import (  # noqa: F401
    RetryPolicy,
    SweepCheckpoint,
    checkpointed_map,
    with_retries,
)

__all__ = [
    "DeviceProfile", "device_profile", "check_device",
    "SolveDiagnostics", "hardened_cholesky", "solve_normal_cholesky",
    "ladder_cholesky_solve",
    "RetryPolicy", "SweepCheckpoint", "checkpointed_map", "with_retries",
]
