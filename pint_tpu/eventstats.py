"""Pulsation-significance statistics for photon phases.

Counterpart of reference ``eventstats.py`` (SURVEY §2): Z^2_m test
(Buccheri et al. 1983), H-test (de Jager et al. 1989/2010), their survival
functions, and sigma conversions.  All accept optional photon weights
(Kerr 2011).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import chi2, norm

__all__ = ["vec", "to_array", "from_array",
           "z2m", "z2mw", "sf_z2m", "cosm", "best_m", "em_four", "em_lc",
           "hm", "hmw", "sf_hm", "sf_h20_dj1989", "sf_h20_dj2010",
           "sig2h20", "sigma_trials", "h2sig", "sig2sigma", "sigma2sig",
           "sf_stackedh"]

TWOPI = 2 * np.pi


def z2m(phases, m: int = 2, weights=None):
    """Z^2_m statistics for harmonics 1..m; returns array of the cumulative
    statistic at each harmonic (reference ``eventstats.py z2m``)."""
    phases = np.asarray(phases, dtype=np.float64)
    n = len(phases)
    if weights is None:
        weights = np.ones(n)
    w = np.asarray(weights, dtype=np.float64)
    # normalization: sum w^2 replaces n for weighted events (Kerr 2011)
    denom = np.sum(w**2)
    ks = np.arange(1, m + 1)
    arg = TWOPI * np.outer(ks, phases)
    c = (np.cos(arg) * w).sum(axis=1)
    s = (np.sin(arg) * w).sum(axis=1)
    return np.cumsum(2.0 / denom * (c**2 + s**2))


def sf_z2m(ts, m: int = 2) -> float:
    """Survival function (p-value) of the Z^2_m statistic: chi2, 2m dof."""
    return float(chi2.sf(ts, 2 * m))


def hm(phases, m: int = 20, c: float = 4.0):
    """H-test: max_k (Z^2_k - c*(k-1)) over k = 1..m
    (reference ``eventstats.py hm``)."""
    zs = z2m(phases, m=m)
    return float(np.max(zs - c * np.arange(m)))


def hmw(phases, weights, m: int = 20, c: float = 4.0):
    """Weighted H-test (Kerr 2011)."""
    zs = z2m(phases, m=m, weights=weights)
    return float(np.max(zs - c * np.arange(m)))


def sf_hm(h: float, m: int = 20, c: float = 4.0) -> float:
    """H-test survival function; the de Jager & Busching (2010) calibration
    sf = exp(-0.4 h) (valid for m=20, c=4)."""
    if m == 20 and c == 4.0:
        return float(np.exp(-0.4 * h))
    # fall back to a conservative chi2 bound on the max statistic
    ks = np.arange(1, m + 1)
    return float(min(1.0, np.sum(chi2.sf(h + c * (ks - 1), 2 * ks))))


def h2sig(h: float) -> float:
    """H-test value -> Gaussian sigma equivalent."""
    return sig2sigma(sf_hm(h))


def sig2sigma(sig: float) -> float:
    """p-value -> one-sided Gaussian sigma (reference ``eventstats.py``)."""
    if sig <= 0:
        return np.inf
    if sig >= 1:
        return 0.0
    return float(norm.isf(sig))


def sigma2sig(sigma: float) -> float:
    """Gaussian sigma -> one-sided p-value."""
    return float(norm.sf(sigma))


def sf_stackedh(k: int, h: float, l: float = 0.398405) -> float:
    """Survival function for the sum of k independent H statistics
    (reference ``eventstats.py sf_stackedh``, Kerr thesis eqn)."""
    import math

    c = l * h
    p = sum(c**i / math.factorial(i) for i in range(k))
    return float(p * np.exp(-c)) if c < 700 else 0.0


def z2mw(phases, weights, m: int = 2):
    """Weighted Z^2_m (CLT-calibrated when weights are well distributed;
    reference ``eventstats.py:157``)."""
    ph = np.asarray(phases) * TWOPI
    w = np.asarray(weights, dtype=np.float64)
    ks = np.arange(1, m + 1)[:, None]
    s = (np.cos(ks * ph) * w).sum(axis=1) ** 2 \
        + (np.sin(ks * ph) * w).sum(axis=1) ** 2
    return np.cumsum(s) * (2.0 / np.sum(w * w))


def cosm(phases, m: int = 2):
    """Cosine test per harmonic (de Jager et al. 1994; reference
    ``eventstats.py:176``)."""
    ph = np.asarray(phases) * TWOPI
    ks = np.arange(1, m + 1)[:, None]
    return (2.0 / len(ph)) * np.cumsum(np.cos(ks * ph).sum(axis=1))


def best_m(phases, weights=None, m: int = 100) -> int:
    """Harmonic count maximizing the H statistic's penalized Z^2
    (reference ``eventstats.py:204``)."""
    w = np.ones(len(phases)) if weights is None else np.asarray(weights)
    z = z2mw(phases, w, m=m)
    return int(np.arange(1, m + 1)[np.argmax(z - 4 * np.arange(0, m))])


def em_four(phases, m: int = 2, weights=None):
    """Empirical Fourier coefficients (a_k, b_k) up to harmonic m
    (reference ``eventstats.py:209``)."""
    ph = np.asarray(phases) * TWOPI
    n = len(ph) if weights is None else np.sum(weights)
    w = 1.0 if weights is None else np.asarray(weights)
    ks = np.arange(1, m + 1)[:, None]
    aks = (w * np.cos(ks * ph)).sum(axis=-1) / n
    bks = (w * np.sin(ks * ph)).sum(axis=-1) / n
    return aks, bks


def em_lc(coeffs, dom):
    """Evaluate the light curve from empirical Fourier coefficients at
    phases in [0, 1) (reference ``eventstats.py:228``)."""
    dom = np.asarray(dom) * TWOPI
    aks, bks = coeffs
    out = np.ones_like(dom)
    for i in range(1, len(aks) + 1):
        out = out + 2 * (aks[i - 1] * np.cos(i * dom)
                         + bks[i - 1] * np.sin(i * dom))
    return out


def sf_h20_dj1989(h: float) -> float:
    """H-test chance probability, de Jager et al. 1989 calibration
    (reference ``eventstats.py:319``; kept for parity — the quadratic term
    is known to be approximate)."""
    if h <= 23:
        return 0.9999755 * np.exp(-0.39802 * h)
    return 4e-8 if h > 50 else 1.210597 * np.exp(-0.45901 * h + 0.00229 * h**2)


def sf_h20_dj2010(h: float) -> float:
    """H-test chance probability, de Jager & Busching 2010 asymptotic."""
    return float(np.exp(-0.4 * h))


def sig2h20(sig: float) -> float:
    """Invert the 2010 calibration: H for a given chance probability."""
    return float(-np.log(sig) / 0.4)


def sigma_trials(sigma: float, trials: float) -> float:
    """Correct a significance for a trials factor (reference
    ``eventstats.py:125``)."""
    if sigma >= 20:
        return float((sigma**2 - 2 * np.log(trials)) ** 0.5)
    p = sigma2sig(sigma) * trials
    return 0.0 if p >= 1 else sig2sigma(p)


def vec(func):
    """Vectorize a scalar statistic, preserving its docstring (reference
    ``eventstats.py:35``)."""
    return np.vectorize(func, doc=func.__doc__)


def to_array(x, dtype=None):
    """Promote a scalar to a 1-element array; pass arrays through
    (reference ``eventstats.py:41``)."""
    x = np.asarray(x, dtype=dtype)
    return np.asarray([x]) if x.ndim == 0 else x


def from_array(x):
    """Inverse of :func:`to_array`: unwrap 1-element arrays (reference
    ``eventstats.py:46``)."""
    return x[0] if (x.ndim == 1) and (x.shape[0] == 1) else x
