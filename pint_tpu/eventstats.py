"""Pulsation-significance statistics for photon phases.

Counterpart of reference ``eventstats.py`` (SURVEY §2): Z^2_m test
(Buccheri et al. 1983), H-test (de Jager et al. 1989/2010), their survival
functions, and sigma conversions.  All accept optional photon weights
(Kerr 2011).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import chi2, norm

__all__ = ["z2m", "sf_z2m", "hm", "hmw", "sf_hm", "h2sig", "sig2sigma",
           "sigma2sig", "sf_stackedh"]

TWOPI = 2 * np.pi


def z2m(phases, m: int = 2, weights=None):
    """Z^2_m statistics for harmonics 1..m; returns array of the cumulative
    statistic at each harmonic (reference ``eventstats.py z2m``)."""
    phases = np.asarray(phases, dtype=np.float64)
    n = len(phases)
    if weights is None:
        weights = np.ones(n)
    w = np.asarray(weights, dtype=np.float64)
    # normalization: sum w^2 replaces n for weighted events (Kerr 2011)
    denom = np.sum(w**2)
    ks = np.arange(1, m + 1)
    arg = TWOPI * np.outer(ks, phases)
    c = (np.cos(arg) * w).sum(axis=1)
    s = (np.sin(arg) * w).sum(axis=1)
    return np.cumsum(2.0 / denom * (c**2 + s**2))


def sf_z2m(ts, m: int = 2) -> float:
    """Survival function (p-value) of the Z^2_m statistic: chi2, 2m dof."""
    return float(chi2.sf(ts, 2 * m))


def hm(phases, m: int = 20, c: float = 4.0):
    """H-test: max_k (Z^2_k - c*(k-1)) over k = 1..m
    (reference ``eventstats.py hm``)."""
    zs = z2m(phases, m=m)
    return float(np.max(zs - c * np.arange(m)))


def hmw(phases, weights, m: int = 20, c: float = 4.0):
    """Weighted H-test (Kerr 2011)."""
    zs = z2m(phases, m=m, weights=weights)
    return float(np.max(zs - c * np.arange(m)))


def sf_hm(h: float, m: int = 20, c: float = 4.0) -> float:
    """H-test survival function; the de Jager & Busching (2010) calibration
    sf = exp(-0.4 h) (valid for m=20, c=4)."""
    if m == 20 and c == 4.0:
        return float(np.exp(-0.4 * h))
    # fall back to a conservative chi2 bound on the max statistic
    ks = np.arange(1, m + 1)
    return float(min(1.0, np.sum(chi2.sf(h + c * (ks - 1), 2 * ks))))


def h2sig(h: float) -> float:
    """H-test value -> Gaussian sigma equivalent."""
    return sig2sigma(sf_hm(h))


def sig2sigma(sig: float) -> float:
    """p-value -> one-sided Gaussian sigma (reference ``eventstats.py``)."""
    if sig <= 0:
        return np.inf
    if sig >= 1:
        return 0.0
    return float(norm.isf(sig))


def sigma2sig(sigma: float) -> float:
    """Gaussian sigma -> one-sided p-value."""
    return float(norm.sf(sigma))


def sf_stackedh(k: int, h: float, l: float = 0.398405) -> float:
    """Survival function for the sum of k independent H statistics
    (reference ``eventstats.py sf_stackedh``, Kerr thesis eqn)."""
    import math

    c = l * h
    p = sum(c**i / math.factorial(i) for i in range(k))
    return float(p * np.exp(-c)) if c < 700 else 0.0
