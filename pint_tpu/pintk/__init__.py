"""Interactive timing interface (counterpart of reference ``pintk/``).

The model/TOA manipulation core (:mod:`pint_tpu.pintk.pulsar`) is GUI-free
and fully scriptable/testable; the Tk widget layer (:mod:`pint_tpu.pintk.plk`)
loads only when tkinter + matplotlib are available.
"""
