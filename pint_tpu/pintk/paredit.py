"""Par-file editor behind the pintk GUI (reference ``pintk/paredit.py``).

The core is GUI-free: it holds the editable par text, validates it by
building a model, and applies it back to the :class:`Pulsar`.  A Tk text
widget wrapping is provided when tkinter is importable, mirroring the
reference's edit/apply/reset/open/write button row.
"""

from __future__ import annotations

from typing import Callable, Optional

from pint_tpu.logging import log

__all__ = ["ParEditor", "ParChoiceWidget"]


class ParEditor:
    """Editable par text bound to a Pulsar (apply/reset/load/write)."""

    def __init__(self, psr, updates_cb: Optional[Callable] = None):
        self.psr = psr
        self.updates_cb = updates_cb
        self.text = self._render()

    def _render(self) -> str:
        return self.psr.model.as_parfile()

    # -- actions (the reference's button row) -------------------------------
    def reset(self) -> str:
        """Discard edits: re-render from the current model."""
        self.text = self._render()
        return self.text

    def set_text(self, text: str) -> None:
        self.text = text

    def check(self):
        """Parse the edited text; returns the would-be model (raises on
        invalid par content without touching the Pulsar)."""
        from pint_tpu.models import get_model

        return get_model(self.text.splitlines(keepends=True))

    def apply(self) -> None:
        """Validate + swap the edited model into the Pulsar (reference
        paredit 'Apply Changes')."""
        model = self.check()
        self.psr.model = model
        self.psr.fitted = False
        self.psr.update_resids()
        if self.updates_cb:
            self.updates_cb()
        log.info("Applied edited par file to the model")

    def load(self, path: str) -> str:
        with open(path) as f:
            self.text = f.read()
        return self.text

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.text)
        log.info(f"Wrote par file to {path}")


class ParChoiceWidget:
    """Tk window with the par text + Apply/Reset/Open/Write buttons."""

    def __init__(self, master, psr, updates_cb=None):
        import tkinter as tk
        from tkinter import filedialog

        self.editor = ParEditor(psr, updates_cb=updates_cb)
        self.win = tk.Toplevel(master)
        self.win.title("pintk: par editor")
        self.textbox = tk.Text(self.win, width=80, height=40)
        self.textbox.pack(side=tk.TOP, fill=tk.BOTH, expand=True)
        self.textbox.insert("1.0", self.editor.text)
        row = tk.Frame(self.win)
        row.pack(side=tk.BOTTOM, fill=tk.X)
        tk.Button(row, text="Apply Changes", command=self._apply).pack(
            side=tk.LEFT)
        tk.Button(row, text="Reset Changes", command=self._reset).pack(
            side=tk.LEFT)
        tk.Button(row, text="Open Par...", command=self._open).pack(
            side=tk.LEFT)
        tk.Button(row, text="Write Par...", command=self._write).pack(
            side=tk.LEFT)
        self._filedialog = filedialog

    def _sync(self):
        self.editor.set_text(self.textbox.get("1.0", "end-1c"))

    def _apply(self):
        self._sync()
        try:
            self.editor.apply()
        except Exception as e:  # surface parse errors in the title bar
            self.win.title(f"pintk: par editor - ERROR: {e}")

    def _reset(self):
        self.textbox.delete("1.0", "end")
        self.textbox.insert("1.0", self.editor.reset())

    def _open(self):
        path = self._filedialog.askopenfilename(title="Open par file")
        if path:
            self.textbox.delete("1.0", "end")
            self.textbox.insert("1.0", self.editor.load(path))

    def _write(self):
        path = self._filedialog.asksaveasfilename(title="Write par file")
        if path:
            self._sync()
            self.editor.write(path)
