"""Tim-file editor behind the pintk GUI (reference ``pintk/timedit.py``).

GUI-free core (edit text, validate by parsing, apply to the Pulsar) plus an
optional Tk wrapping, parallel to :mod:`pint_tpu.pintk.paredit`.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Optional

from pint_tpu.logging import log

__all__ = ["TimEditor", "TimChoiceWidget"]


class TimEditor:
    """Editable tim text bound to a Pulsar (apply/reset/load/write)."""

    def __init__(self, psr, updates_cb: Optional[Callable] = None):
        self.psr = psr
        self.updates_cb = updates_cb
        self.text = self._render()

    def _render(self) -> str:
        tmp = tempfile.NamedTemporaryFile("w", suffix=".tim", delete=False)
        tmp.close()
        try:
            self.psr.all_toas.write_TOA_file(tmp.name)
            with open(tmp.name) as f:
                return f.read()
        finally:
            os.unlink(tmp.name)

    def reset(self) -> str:
        self.text = self._render()
        return self.text

    def set_text(self, text: str) -> None:
        self.text = text

    def check(self):
        """Parse the edited text; returns the would-be TOAs (raises on
        invalid tim content without touching the Pulsar)."""
        from pint_tpu.toa import get_TOAs

        tmp = tempfile.NamedTemporaryFile("w", suffix=".tim", delete=False)
        tmp.write(self.text)
        tmp.close()
        try:
            return get_TOAs(tmp.name, model=self.psr.model)
        finally:
            os.unlink(tmp.name)

    def apply(self) -> None:
        toas = self.check()
        self.psr.all_toas = toas
        self.psr.selected_toas = toas
        self.psr.fitted = False
        self.psr.update_resids()
        if self.updates_cb:
            self.updates_cb()
        log.info(f"Applied edited tim file: {len(toas)} TOAs")

    def load(self, path: str) -> str:
        with open(path) as f:
            self.text = f.read()
        return self.text

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.text)
        log.info(f"Wrote tim file to {path}")


class TimChoiceWidget:
    """Tk window with the tim text + Apply/Reset/Open/Write buttons."""

    def __init__(self, master, psr, updates_cb=None):
        import tkinter as tk
        from tkinter import filedialog

        self.editor = TimEditor(psr, updates_cb=updates_cb)
        self.win = tk.Toplevel(master)
        self.win.title("pintk: tim editor")
        self.textbox = tk.Text(self.win, width=100, height=40)
        self.textbox.pack(side=tk.TOP, fill=tk.BOTH, expand=True)
        self.textbox.insert("1.0", self.editor.text)
        row = tk.Frame(self.win)
        row.pack(side=tk.BOTTOM, fill=tk.X)
        tk.Button(row, text="Apply Changes", command=self._apply).pack(
            side=tk.LEFT)
        tk.Button(row, text="Reset Changes", command=self._reset).pack(
            side=tk.LEFT)
        tk.Button(row, text="Open Tim...", command=self._open).pack(
            side=tk.LEFT)
        tk.Button(row, text="Write Tim...", command=self._write).pack(
            side=tk.LEFT)
        self._filedialog = filedialog

    def _sync(self):
        self.editor.set_text(self.textbox.get("1.0", "end-1c"))

    def _apply(self):
        self._sync()
        try:
            self.editor.apply()
        except Exception as e:
            self.win.title(f"pintk: tim editor - ERROR: {e}")

    def _reset(self):
        self.textbox.delete("1.0", "end")
        self.textbox.insert("1.0", self.editor.reset())

    def _open(self):
        path = self._filedialog.askopenfilename(title="Open tim file")
        if path:
            self.textbox.delete("1.0", "end")
            self.textbox.insert("1.0", self.editor.load(path))

    def _write(self):
        path = self._filedialog.asksaveasfilename(title="Write tim file")
        if path:
            self._sync()
            self.editor.write(path)
