"""TOA coloring modes for the pintk residual plot (reference
``pintk/colormodes.py``: DefaultMode, FreqMode, NameMode, ObsMode,
JumpMode).

Redesigned headless-first: each mode maps a :class:`pint_tpu.pintk.pulsar
.Pulsar` (+ selection mask) to a per-TOA color array and a {label: color}
legend, so the logic is testable without tkinter; the plk widget just
scatters with the returned colors.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

__all__ = ["ColorMode", "DefaultMode", "FreqMode", "NameMode", "ObsMode",
           "JumpMode", "COLOR_MODES", "get_color_mode"]

SELECTED_COLOR = "#d03020"


class ColorMode:
    """Base: compute per-TOA plot colors for one coloring scheme.

    Modes implement ``_groups(psr) -> [(label, color, mask)]``; later groups
    override earlier ones where masks overlap (jump layering).  Labels are
    unique even when palette colors repeat, so plotting by *group* never
    double-draws points the way color-equality grouping would.
    """

    mode_name = "base"

    def _groups(self, psr):
        raise NotImplementedError

    def get_groups(self, psr, selected=None):
        """[(label, color, mask)] with overlaps resolved (each TOA belongs
        to exactly one group) and the selection appended last."""
        n = len(psr.all_toas)
        raw = self._groups(psr)
        claimed = np.zeros(n, dtype=bool)
        out = []
        # later groups take precedence: walk in reverse, keep first claim
        for label, color, mask in reversed(raw):
            mask = np.asarray(mask, dtype=bool) & ~claimed
            claimed |= mask
            out.append((label, color, mask))
        out.reverse()
        if selected is not None and np.any(selected):
            sel = np.asarray(selected, dtype=bool)
            out = [(lbl, c, m & ~sel) for lbl, c, m in out]
            out.append(("selected", SELECTED_COLOR, sel))
        return [(lbl, c, m) for lbl, c, m in out if m.any()]

    def get_colors(self, psr, selected=None) -> Tuple[np.ndarray, Dict[str, str]]:
        """(colors (N,) of str, legend {label: color}); ``selected`` TOAs
        override with the selection color."""
        n = len(psr.all_toas)
        colors = np.full(n, DefaultMode.color, dtype=object)
        legend = {}
        for label, color, mask in self.get_groups(psr, selected):
            colors[mask] = color
            legend[label] = color
        return colors, legend

    def display_info(self, psr) -> str:
        _, legend = self.get_colors(psr)
        lines = [f'"{self.mode_name}" mode:']
        lines += [f"  {lbl:<12s} {col}" for lbl, col in legend.items()]
        return "\n".join(lines)


class DefaultMode(ColorMode):
    """All TOAs one color (reference ``colormodes.py:45``)."""

    mode_name = "default"
    color = "#2060a0"

    def _groups(self, psr):
        n = len(psr.all_toas)
        return [("TOA", self.color, np.ones(n, dtype=bool))]


class FreqMode(ColorMode):
    """Color by radio frequency band (reference ``colormodes.py:92`` band
    edges: 300/400/500/700/1000/1800/3000/8000 MHz)."""

    mode_name = "freq"
    edges = [300.0, 400.0, 500.0, 700.0, 1000.0, 1800.0, 3000.0, 8000.0]
    band_colors = ["#8b0000", "#e50000", "#f97306", "#ffff14", "#15b01a",
                   "#0343df", "#380282", "#000000", "#929591"]
    band_labels = ["<300", "300-400", "400-500", "500-700", "700-1000",
                   "1000-1800", "1800-3000", "3000-8000", ">8000"]

    def _groups(self, psr):
        freqs = np.asarray(psr.all_toas.freq_mhz, dtype=np.float64)
        band = np.digitize(freqs, self.edges)
        return [(f"{lbl} MHz", self.band_colors[b], band == b)
                for b, lbl in enumerate(self.band_labels)
                if np.any(band == b)]


_CYCLE = ["#e50000", "#15b01a", "#0343df", "#f97306", "#7e1e9c", "#00ffff",
          "#653700", "#ff81c0", "#929591", "#000000"]


class NameMode(ColorMode):
    """Color by the TOA's source name flag (``-name`` / tim file), cycling a
    fixed palette (reference ``colormodes.py:177``)."""

    mode_name = "name"

    def _groups(self, psr):
        toas = psr.all_toas
        names = np.asarray([fl.get("name", toas.filename or "?")
                            for fl in toas.flags], dtype=object)
        return [(str(nm), _CYCLE[i % len(_CYCLE)], names == nm)
                for i, nm in enumerate(sorted(set(names)))]


class ObsMode(ColorMode):
    """Color by observatory, with the reference's site grouping (any gb* is
    Green Bank, jb* is Jodrell, *stl* is space; reference
    ``colormodes.py:237``)."""

    mode_name = "obs"
    obs_colors = {
        "parkes": "#e50000", "gb": "#15b01a", "jodrell": "#00ffff",
        "arecibo": "#0343df", "chime": "#c04e01", "gmrt": "#653700",
        "vla": "#380282", "effelsberg": "#7e1e9c", "fast": "#00035b",
        "nancay": "#96f97b", "srt": "#033500", "wsrt": "#95d0fc",
        "lofar": "#840000", "lwa": "#840000", "mwa": "#840000",
        "meerkat": "#c20078", "barycenter": "#929591", "space": "#000000",
        "other": "#d8dcd6",
    }

    @staticmethod
    def _group(site: str) -> str:
        s = site.lower()
        if "stl" in s:
            return "space"
        if s.startswith("gb"):
            return "gb"
        if s.startswith("jb"):
            return "jodrell"
        if "ncy" in s:
            return "nancay"
        return s if s in ObsMode.obs_colors else "other"

    def _groups(self, psr):
        obs = np.asarray(psr.all_toas.obs, dtype=object)
        groups = np.asarray([self._group(str(o)) for o in obs], dtype=object)
        return [(g, self.obs_colors[g], groups == g)
                for g in sorted(set(groups))]


class JumpMode(ColorMode):
    """Color TOAs by which JUMP selects them (reference
    ``colormodes.py:345``); un-jumped TOAs keep the default color."""

    mode_name = "jump"
    base_color = DefaultMode.color

    def _groups(self, psr):
        toas = psr.all_toas
        n = len(toas)
        out = [("no jump", self.base_color, np.ones(n, dtype=bool))]
        comp = psr.model.components.get("PhaseJump")
        if comp is not None:
            k = 0
            for jname in comp.jumps:
                par = comp._params_dict[jname]
                if par.key is None and not par.key_value:
                    continue  # unconfigured placeholder selects everything
                mask = np.zeros(n, dtype=bool)
                mask[np.asarray(par.select_toa_mask(toas), dtype=int)] = True
                out.append((jname, _CYCLE[k % len(_CYCLE)], mask))
                k += 1
        return out


COLOR_MODES = {cls.mode_name: cls for cls in
               (DefaultMode, FreqMode, NameMode, ObsMode, JumpMode)}


def get_color_mode(name: str) -> ColorMode:
    try:
        return COLOR_MODES[name]()
    except KeyError:
        raise ValueError(f"Unknown color mode {name!r}; "
                         f"choose from {sorted(COLOR_MODES)}")
