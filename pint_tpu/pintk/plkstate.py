"""GUI-independent pintk interaction state (reference ``pintk/plk.py``).

Everything the reference's PlkWidget does in Tk callbacks — axis choice,
per-point select/delete, stash, phase wraps, jumps, fit-parameter
checkboxes, log-level — lives here as plain state functions over a
:class:`~pint_tpu.pintk.pulsar.Pulsar`, so the whole interaction surface is
headlessly testable (select -> delete -> refit without a display) and the
Tk layer in ``plk.py`` stays a thin binding.  Reference behaviors:
axis ids and labels ``plk.py:39 plotlabels``, ``plk.py:581 setChoice``;
click select / delete / stash keys ``plk.py:760+`` helpstring.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pint_tpu.logging import log

__all__ = ["PlkState", "XIDS", "YIDS", "plotlabels"]

#: x-axis choice ids (reference ``plk.py plotlabels`` keys)
XIDS = ("mjd", "year", "day of year", "serial", "orbital phase",
        "frequency", "TOA error", "rounded MJD", "elongation")
#: y-axis choice ids
YIDS = ("pre-fit", "post-fit", "white-res")

plotlabels = {
    "mjd": "MJD", "year": "Year", "day of year": "Day of the year",
    "serial": "TOA number", "orbital phase": "Orbital Phase",
    "frequency": "Observing Frequency (MHz)",
    "TOA error": "TOA uncertainty (us)", "rounded MJD": "MJD",
    "elongation": "Solar Elongation (deg)",
    "pre-fit": "Pre-fit residual (us)", "post-fit": "Post-fit residual (us)",
    "white-res": "Whitened residual",
}


class PlkState:
    """Interaction state over a Pulsar: selection mask, axis ids, stash."""

    def __init__(self, psr):
        self.psr = psr
        self.xid = "mjd"
        self.yid = "pre-fit"
        self.selected = np.zeros(len(psr.all_toas), dtype=bool)
        self.random_overlay = False
        self.colormode = "default"
        self._stash = None  # (stashed TOAs object) when 't' stashed
        self.last_resids = None  # set by yvals(); reused for the title

    # -- axis data -----------------------------------------------------------
    def set_choice(self, xid: Optional[str] = None,
                   yid: Optional[str] = None) -> None:
        """Pick the plotted quantities (reference ``plk.py:581``)."""
        if xid is not None:
            if xid not in XIDS:
                raise ValueError(f"unknown x-axis id {xid!r}; one of {XIDS}")
            self.xid = xid
        if yid is not None:
            if yid not in YIDS:
                raise ValueError(f"unknown y-axis id {yid!r}; one of {YIDS}")
            self.yid = yid

    def xvals(self) -> np.ndarray:
        psr, xid = self.psr, self.xid
        mjds = np.asarray(psr.all_toas.get_mjds(), dtype=np.float64)
        if xid == "mjd":
            return mjds
        if xid == "rounded MJD":
            return np.floor(mjds + 0.5)
        if xid == "year":
            return psr.year()
        if xid == "day of year":
            return psr.dayofyear()
        if xid == "serial":
            return np.arange(len(mjds), dtype=np.float64)
        if xid == "orbital phase":
            return psr.orbitalphase()
        if xid == "frequency":
            f = np.asarray(psr.all_toas.get_freqs(), dtype=np.float64)
            return np.where(np.isfinite(f), f, 0.0)
        if xid == "TOA error":
            return np.asarray(psr.all_toas.get_errors(), dtype=np.float64)
        if xid == "elongation":
            for comp in psr.model.components.values():
                if hasattr(comp, "sun_angle"):
                    return np.degrees(np.asarray(
                        comp.sun_angle(psr.all_toas)))
            log.warning("no astrometry component: elongation = 0")
            return np.zeros(len(mjds))
        raise ValueError(xid)

    def yvals(self) -> Tuple[np.ndarray, np.ndarray]:
        """(values, errors) in the y quantity's units (us for residuals).

        'pre-fit' is measured against the INITIAL model (``prefit_resids``,
        kept vs model_init) so it stays distinct from 'post-fit' after a
        fit.  The residuals object actually used is left in
        ``self.last_resids`` so a caller (the plot title) need not rebuild
        it."""
        psr = self.psr
        errs = np.asarray(psr.all_toas.get_errors(), dtype=np.float64)
        if psr.prefit_resids is None or \
                len(np.asarray(psr.prefit_resids.resids)) != len(errs):
            psr.update_resids()  # TOA edits leave cached residuals stale
        if self.yid == "pre-fit":
            r = psr.prefit_resids
        elif psr.fitted:
            r = psr.postfit_resids
        else:
            if self.yid == "post-fit":
                log.warning("not fitted yet: post-fit shows pre-fit")
            r = psr.prefit_resids
        self.last_resids = r
        if self.yid == "white-res":
            return np.asarray(r.calc_whitened_resids()), np.ones_like(errs)
        return np.asarray(r.time_resids) * 1e6, errs

    # -- selection -----------------------------------------------------------
    def _check_mask(self) -> None:
        if len(self.selected) != len(self.psr.all_toas):
            self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)

    def select_rect(self, x1: float, x2: float, y1: float, y2: float,
                    append: bool = True) -> int:
        """Add (or replace) the rectangle's points; returns selected count."""
        self._check_mask()
        x, (y, _) = self.xvals(), self.yvals()
        m = (x >= min(x1, x2)) & (x <= max(x1, x2)) \
            & (y >= min(y1, y2)) & (y <= max(y1, y2))
        self.selected = (self.selected | m) if append else m
        return int(self.selected.sum())

    def nearest_point(self, x: float, y: float,
                      max_dist: float = 0.05) -> Optional[int]:
        """Index of the closest point in axis-normalized distance, or None
        (the reference's click tolerance, ``plk.py closest point``)."""
        self._check_mask()
        if len(self.psr.all_toas) == 0:
            return None
        xv, (yv, _) = self.xvals(), self.yvals()
        xs = np.ptp(xv) or 1.0
        ys = np.ptp(yv) or 1.0
        d = np.hypot((xv - x) / xs, (yv - y) / ys)
        i = int(np.argmin(d))
        return i if d[i] <= max_dist else None

    def toggle_point(self, x: float, y: float) -> Optional[int]:
        """Left click: toggle the nearest point's selection."""
        i = self.nearest_point(x, y)
        if i is not None:
            self.selected[i] = ~self.selected[i]
        return i

    def unselect_all(self) -> None:  # 'u'
        self._check_mask()
        self.selected[:] = False

    # -- deletion / stash ----------------------------------------------------
    def delete_point(self, x: float, y: float) -> Optional[int]:
        """Right click: permanently delete the nearest point.  The existing
        selection survives (shifted past the removed index)."""
        if len(self.psr.all_toas) <= 1:
            log.warning("refusing to delete the last TOA")
            return None
        i = self.nearest_point(x, y)
        if i is not None:
            self._check_mask()
            self.psr.delete_TOAs([i])
            self.selected = np.delete(self.selected, i)
        return i

    def delete_selected(self) -> int:  # 'd'
        self._check_mask()
        n = int(self.selected.sum())
        if n >= len(self.psr.all_toas):
            log.warning("refusing to delete every TOA")
            return 0
        if n:
            self.psr.delete_TOAs(np.nonzero(self.selected)[0])
            self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
        return n

    def stash_selected(self) -> int:
        """'t': temporarily remove the selected TOAs (or un-stash when the
        selection is empty and a stash exists, like the reference)."""
        self._check_mask()
        if not self.selected.any():
            return -self.unstash()
        self._stash = self.psr.all_toas
        self.psr.all_toas = self.psr.all_toas[~self.selected]
        self.psr.reset_selection()
        self.psr.update_resids()
        n = int(self.selected.sum())
        self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
        return n

    def unstash(self) -> int:
        if self._stash is None:
            return 0
        restored = len(self._stash) - len(self.psr.all_toas)
        self.psr.all_toas = self._stash
        self._stash = None
        self.psr.reset_selection()
        self.psr.update_resids()
        self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)
        return restored

    # -- model interactions --------------------------------------------------
    def phase_wrap(self, n: int) -> None:
        self._check_mask()
        if self.selected.any():
            self.psr.add_phase_wrap(self.selected, n)

    def jump_selected(self) -> Optional[str]:  # 'j'
        self._check_mask()
        if self.selected.any():
            return self.psr.add_jump(self.selected)
        return None

    def fit(self, iters: int = 4) -> float:
        """'f': fit the selected TOAs, or all when none selected."""
        self._check_mask()
        if self.selected.any():
            self.psr.select_toas(self.selected)
            chi2 = self.psr.fit(selected=True, iters=iters)
        else:
            chi2 = self.psr.fit(iters=iters)
        return chi2

    def reset(self) -> None:  # 'r'
        self.psr.resetAll()
        self._stash = None
        self.selected = np.zeros(len(self.psr.all_toas), dtype=bool)

    # -- fit-parameter checkboxes -------------------------------------------
    def fit_checkboxes(self) -> list:
        """[(param, is_fit)] over the model's fittable parameters."""
        return [(p, not getattr(self.psr.model, p).frozen)
                for p in self.psr.model.fittable_params]

    def set_fit(self, param: str, fit: bool) -> None:
        self.psr.set_fit_state(param, fit)

    def get_fit(self, param: str) -> bool:
        return not getattr(self.psr.model, param).frozen

    # -- log level (reference log-level dropdown) ---------------------------
    def set_loglevel(self, level: str) -> None:
        import logging as _pylog

        log.setLevel(getattr(_pylog, level.upper()))
