"""Pulsar: the model+TOAs wrapper behind the interactive GUI.

Counterpart of reference ``pintk/pulsar.py`` (700 LoC): owns the timing
model, the full and selected TOAs, pre/post-fit residuals, and the editing
operations the GUI exposes — fitting, parameter freeze/thaw, phase wraps,
jump add/remove on selections, random-model draws.  Entirely GUI-free so it
doubles as a scripting convenience ("the pintk workflow without Tk").
"""

from __future__ import annotations

import copy
from typing import List, Optional

import numpy as np

from pint_tpu.fitter import Fitter
from pint_tpu.logging import log
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.toa import get_TOAs

__all__ = ["Pulsar"]

#: fitter-name -> constructor used by the GUI fitter selector
FITTER_NAMES = ["auto", "WLS", "GLS", "downhill WLS", "downhill GLS",
                "Wideband"]


class Pulsar:
    def __init__(self, parfile: str, timfile: str, ephem: Optional[str] = None,
                 fitter: str = "auto"):
        self.parfile = parfile
        self.timfile = timfile
        self.ephem = ephem
        self.model_init = get_model(parfile)
        self.model = copy.deepcopy(self.model_init)
        self.all_toas = get_TOAs(timfile, model=self.model, ephem=ephem)
        self.selected_toas = self.all_toas
        self.fit_method = fitter
        self.fitted = False
        self.track_added = False
        self.fitter: Optional[Fitter] = None
        self.prefit_resids = Residuals(self.all_toas, self.model)
        self.postfit_resids: Optional[Residuals] = None

    # -- basic info ----------------------------------------------------------
    @property
    def name(self) -> str:
        return str(self.model.PSR.value or "")

    def __getitem__(self, key):
        return getattr(self.model, key)

    def __contains__(self, key) -> bool:
        return key in self.model.params

    # -- plot-axis helpers (reference ``pintk/pulsar.py:256-286``) ----------
    def orbitalphase(self) -> np.ndarray:
        """Orbital phase of every TOA in cycles [0, 1); zeros for a
        non-binary pulsar (reference ``pintk/pulsar.py:256``)."""
        if not self.model.is_binary:
            log.warning("This is not a binary pulsar")
            return np.zeros(len(self.all_toas))
        mjds = np.asarray(self.all_toas.get_mjds(), dtype=np.float64)
        return self.model.orbital_phase(mjds, anom="mean", radians=False)

    def year(self) -> np.ndarray:
        """Decimal year of every TOA (reference ``pintk/pulsar.py:280``)."""
        mjds = np.asarray(self.all_toas.get_mjds(), dtype=np.float64)
        # MJD 51544.5 = 2000.0; Julian year = 365.25 d
        return 2000.0 + (mjds - 51544.5) / 365.25

    def dayofyear(self) -> np.ndarray:
        """Days since the start of each TOA's (Julian) year (reference
        ``pintk/pulsar.py:272``)."""
        mjds = np.asarray(self.all_toas.get_mjds(), dtype=np.float64)
        yr = np.floor(self.year())
        year_start_mjd = 51544.5 + (yr - 2000.0) * 365.25
        return mjds - year_start_mjd

    def add_model_params(self) -> None:
        """Expose the next unfit spin / orbital-frequency derivative so the
        GUI can offer it (reference ``pintk/pulsar.py:287``): when F<n-1>
        (or FB<n-1>) is free and F<n> absent, add it frozen at zero."""
        m = self.model
        if "Spindown" in m.components:
            c = m.components["Spindown"]
            # count only params with a value: F1 exists by construction but
            # may be unset when the par file stops at F0
            fs = sorted(int(p[1:]) for p in c.params
                        if p.startswith("F") and p[1:].isdigit()
                        and c._params_dict[p].value is not None)
            n = max(fs) + 1
            if f"F{n - 1}" in m.free_params:
                if f"F{n}" in c._params_dict:
                    c._params_dict[f"F{n}"].value = 0.0
                    c._params_dict[f"F{n}"].frozen = True
                else:
                    c.add_param(c._params_dict["F1"].new_param(n, value=0.0),
                                setup=True)
                    getattr(m, f"F{n}").units = f"Hz/s^{n}"
        for comp in m.components.values():
            if not type(comp).__name__.startswith("Binary"):
                continue
            fbs = sorted(int(p[2:]) for p in comp.params
                         if p.startswith("FB") and p[2:].isdigit()
                         and comp._params_dict[p].value is not None)
            if fbs:
                n = max(fbs) + 1
                if f"FB{n - 1}" in m.free_params \
                        and f"FB{n}" not in comp._params_dict:
                    comp.add_param(
                        comp._params_dict["FB0"].new_param(n, value=0.0),
                        setup=True)
        m.setup()

    def resetAll(self) -> None:
        """Reload the model and TOAs from disk (reference
        ``pintk/pulsar.py:177``)."""
        self.model_init = get_model(self.parfile)
        self.model = copy.deepcopy(self.model_init)
        self.fitted = False
        self.fitter = None
        self.postfit_resids = None
        # reset_TOAs re-ingests and rebuilds residuals once; going through
        # reset_model first would build them twice against stale TOAs
        self.reset_TOAs()

    def print_chi2(self, selected=None) -> str:
        """Chi2 summary for the selection (reference
        ``pintk/pulsar.py:498``); returns and prints the text.  ``selected``
        is a boolean mask or index array; an empty/None selection means
        all TOAs."""
        if selected is None:
            toas = self.all_toas
        else:
            selected = np.asarray(selected)
            if selected.dtype == bool:
                use_all = not selected.any()
            else:
                use_all = selected.size == 0  # index arrays may contain 0
            toas = self.all_toas if use_all else self.all_toas[selected]
        r = Residuals(toas, self.model)
        text = (f"Chisq = {r.chi2:.6f} for {r.dof} d.o.f. "
                f"-> reduced chisq = {r.chi2 / max(r.dof, 1):.6f}")
        print(text)
        return text

    # -- residuals -----------------------------------------------------------
    def resids(self, selected: bool = False) -> Residuals:
        toas = self.selected_toas if selected else self.all_toas
        return Residuals(toas, self.model)

    def update_resids(self):
        self.prefit_resids = Residuals(self.all_toas, self.model_init)
        if self.fitted:
            self.postfit_resids = Residuals(self.all_toas, self.model)

    # -- selection -----------------------------------------------------------
    def select_toas(self, mask) -> None:
        """Restrict the working set (boolean mask or index array)."""
        self.selected_toas = self.all_toas[mask]

    def reset_selection(self):
        self.selected_toas = self.all_toas

    def delete_TOAs(self, indices) -> None:
        keep = np.ones(len(self.all_toas), dtype=bool)
        keep[np.asarray(indices)] = False
        self.all_toas = self.all_toas[keep]
        self.reset_selection()
        self.update_resids()

    # -- model editing -------------------------------------------------------
    def set_fit_state(self, param: str, fit: bool):
        getattr(self.model, param).frozen = not fit

    def free_params(self) -> List[str]:
        return self.model.free_params

    def add_phase_wrap(self, selected_mask, phase: int):
        """Add integer phase wraps to the selected TOAs (reference
        ``pintk/pulsar.py add_phase_wrap``)."""
        toas = self.all_toas
        if toas.pulse_number is None:
            toas.compute_pulse_numbers(self.model)
        dpn = toas.delta_pulse_number
        if dpn is None:
            dpn = np.zeros(len(toas))
        dpn = np.asarray(dpn, dtype=np.float64).copy()
        dpn[np.asarray(selected_mask)] += phase
        toas.delta_pulse_number = dpn
        toas._version += 1
        self.update_resids()

    def add_jump(self, selected_mask) -> str:
        """JUMP the selected TOAs: flags them with -gui_jump and adds the
        mask parameter (reference ``pintk/pulsar.py add_jump``)."""
        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.parameter import maskParameter

        if "PhaseJump" not in self.model.components:
            self.model.add_component(PhaseJump(), validate=False)
        comp = self.model.components["PhaseJump"]
        idx = 1 + sum(1 for p in comp.params if p.startswith("JUMP"))
        flagval = str(idx)
        for i in np.nonzero(np.asarray(selected_mask))[0]:
            self.all_toas.flags[i]["gui_jump"] = flagval
        self.all_toas._version += 1
        name = f"JUMP{idx}"
        if name not in comp.params:
            par = maskParameter("JUMP", index=idx, key="-gui_jump",
                               key_value=[flagval], units="s", value=0.0,
                               frozen=False)
            comp.add_param(par)
        self.model.setup()
        return name

    def getDefaultFitter(self) -> str:
        if getattr(self.all_toas, "wideband", False):
            return "Wideband"
        return "downhill GLS" if self.model.has_correlated_errors \
            else "downhill WLS"

    # -- fitting -------------------------------------------------------------
    def fit(self, selected: bool = False, iters: int = 4) -> float:
        toas = self.selected_toas if selected else self.all_toas
        self.fitter = Fitter.auto(toas, self.model) \
            if self.fit_method == "auto" else self._make_fitter(toas)
        chi2 = self.fitter.fit_toas(maxiter=iters)
        self.model = self.fitter.model
        self.fitted = True
        self.update_resids()
        return chi2

    def _make_fitter(self, toas):
        from pint_tpu.fitter import DownhillWLSFitter, WLSFitter
        from pint_tpu.gls_fitter import DownhillGLSFitter, GLSFitter

        table = {"WLS": WLSFitter, "GLS": GLSFitter,
                 "downhill WLS": DownhillWLSFitter,
                 "downhill GLS": DownhillGLSFitter}
        if self.fit_method == "Wideband":
            from pint_tpu.wideband import WidebandTOAFitter

            return WidebandTOAFitter(toas, self.model)
        return table[self.fit_method](toas, self.model)

    def reset_model(self):
        self.model = copy.deepcopy(self.model_init)
        self.fitted = False
        self.postfit_resids = None
        self.update_resids()

    def reset_TOAs(self):
        self.all_toas = get_TOAs(self.timfile, model=self.model,
                                 ephem=self.ephem)
        self.reset_selection()
        self.update_resids()

    def write_fit_summary(self) -> str:
        return self.fitter.get_summary() if self.fitter else "(not fitted)"

    def random_models(self, nmodels: int = 30, rng=None,
                      keep_models: bool = True):
        """Random model phase predictions for the GUI overlay
        (reference ``pintk/pulsar.py random_models``)."""
        from pint_tpu.simulation import calculate_random_models

        if self.fitter is None:
            raise ValueError("Fit first")
        return calculate_random_models(self.fitter, self.all_toas,
                                       Nmodels=nmodels, rng=rng,
                                       keep_models=keep_models)
