"""Tk plotting widget for interactive timing (reference ``pintk/plk.py``).

A thin Tk+matplotlib binding over :class:`pint_tpu.pintk.plkstate.PlkState`
— selection, per-point delete, phase wraps, jumps, x/y-axis choice,
fit-parameter checkboxes, random-model overlay and log-level all live in
the GUI-independent state object (headlessly tested); this module only
wires widgets and events to it.  Imports of tkinter/matplotlib happen at
call time so headless deployments (and the --test CI path) never touch
them.  Reference interactions: ``pintk/plk.py:760+`` helpstring (left
click select, right click delete, f fit, d delete, t stash, u unselect,
j jump, r reset).
"""

from __future__ import annotations

import numpy as np

__all__ = ["launch_gui"]


def launch_gui(psr):
    import tkinter as tk
    from tkinter import ttk

    import matplotlib

    matplotlib.use("TkAgg")
    from matplotlib.backends.backend_tkagg import FigureCanvasTkAgg
    from matplotlib.figure import Figure
    from matplotlib.widgets import RectangleSelector

    from pint_tpu.pintk.colormodes import COLOR_MODES, get_color_mode
    from pint_tpu.pintk.plkstate import XIDS, YIDS, PlkState, plotlabels

    st = PlkState(psr)
    overlay_cache = {}

    root = tk.Tk()
    root.title(f"pintk: {psr.name}")
    fig = Figure(figsize=(9, 5.5))
    ax = fig.add_subplot(111)
    canvas = FigureCanvasTkAgg(fig, master=root)
    canvas.get_tk_widget().pack(side=tk.TOP, fill=tk.BOTH, expand=1)

    def redraw():
        ax.clear()
        st._check_mask()
        x = st.xvals()
        y, yerr = st.yvals()
        sel = st.selected
        groups = get_color_mode(st.colormode).get_groups(psr, sel)
        for lbl, col, m in groups:
            ax.errorbar(x[m], y[m], yerr=yerr[m], fmt=".", color=col,
                        ecolor="0.8", label=lbl)
        if len(groups) > 1:
            ax.legend(loc="upper right", fontsize=7)
        if st.random_overlay and psr.fitted and st.xid == "mjd" \
                and st.yid in ("pre-fit", "post-fit"):
            # random-model overlay (us-unit deltas: only meaningful on the
            # residual-in-us views), cached per fit: recomputing re-jits 12
            # model copies per click.  TOA edits invalidate the cache (the
            # draws are per-TOA and would broadcast-error after a delete).
            try:
                if overlay_cache.get("n") != len(psr.all_toas):
                    overlay_cache.clear()
                if overlay_cache.get("draws") is None:
                    overlay_cache["draws"] = psr.random_models(
                        nmodels=12, keep_models=False)
                    overlay_cache["n"] = len(psr.all_toas)
                dphase = overlay_cache["draws"]
                order = np.argsort(x)
                F0 = float(psr.model.F0.value)
                for k in range(dphase.shape[0]):
                    ax.plot(x[order], (y + dphase[k] / F0 * 1e6)[order],
                            color="#f0a030", alpha=0.35, lw=0.7, zorder=0)
            except Exception as e:
                from pint_tpu.logging import log

                log.warning(f"random-model overlay unavailable: {e}")
        ax.axhline(0, color="0.5", lw=0.8)
        ax.set_xlabel(plotlabels[st.xid])
        ax.set_ylabel(plotlabels[st.yid])
        r = st.last_resids  # the residuals yvals() just built
        ax.set_title(f"{psr.name}  chi2={r.chi2:.2f}/{r.dof}")
        canvas.draw()

    def on_select(eclick, erelease):
        # a zero-drag left click is a single-point toggle (reference 'left
        # click select'); a real drag is a rectangle selection.  PIXEL
        # distance discriminates: a data-space threshold would misread a
        # few-day drag on a decade-long axis as a click.
        if abs(erelease.x - eclick.x) < 3 and abs(erelease.y - eclick.y) < 3:
            st.toggle_point(eclick.xdata, eclick.ydata)
        else:
            st.select_rect(eclick.xdata, erelease.xdata,
                           eclick.ydata, erelease.ydata)
        redraw()

    selector = RectangleSelector(ax, on_select, useblit=True, button=[1])

    def on_click(event):
        if event.inaxes != ax or event.xdata is None:
            return
        if event.button == 3:  # right click: delete nearest point
            if st.delete_point(event.xdata, event.ydata) is not None:
                redraw()

    def on_key(event):
        if event.key == "f":
            do_fit()
        elif event.key == "d":
            if st.delete_selected():
                redraw()
        elif event.key == "t":
            st.stash_selected()
            redraw()
        elif event.key == "u":
            st.unselect_all()
            redraw()
        elif event.key == "j":
            if st.jump_selected():
                redraw()
        elif event.key == "r":
            st.reset()
            overlay_cache.clear()
            redraw()

    canvas.mpl_connect("button_press_event", on_click)
    canvas.mpl_connect("key_press_event", on_key)

    bar = ttk.Frame(root)
    bar.pack(side=tk.BOTTOM, fill=tk.X)

    def do_fit():
        st.fit()
        overlay_cache.clear()  # new covariance -> new draws
        redraw()

    def do_reset():
        psr.reset_model()
        st.unselect_all()
        redraw()

    def do_random():
        st.random_overlay = not st.random_overlay
        redraw()

    def do_paredit():
        from pint_tpu.pintk.paredit import ParChoiceWidget

        ParChoiceWidget(root, psr, updates_cb=redraw)

    def do_timedit():
        from pint_tpu.pintk.timedit import TimChoiceWidget

        TimChoiceWidget(root, psr, updates_cb=redraw)

    # color-mode / axis / log-level selectors
    def combo(parent, label, values, init, cb, width=9):
        ttk.Label(parent, text=label).pack(side=tk.RIGHT)
        var = tk.StringVar(value=init)

        def on_change(_ev=None):
            cb(var.get())
            redraw()

        c = ttk.Combobox(parent, textvariable=var, width=width,
                         values=list(values), state="readonly")
        c.bind("<<ComboboxSelected>>", on_change)
        c.pack(side=tk.RIGHT)
        return var

    combo(bar, "Color:", sorted(COLOR_MODES), "default",
          lambda v: setattr(st, "colormode", v))
    combo(bar, "Y:", YIDS, st.yid, lambda v: st.set_choice(yid=v))
    combo(bar, "X:", XIDS, st.xid, lambda v: st.set_choice(xid=v), width=12)
    combo(bar, "Log:", ("DEBUG", "INFO", "WARNING", "ERROR"), "INFO",
          lambda v: st.set_loglevel(v), width=8)

    for label, cmd in [("Fit", do_fit), ("Reset", do_reset),
                       ("Clear sel", lambda: (st.unselect_all(), redraw())),
                       ("Delete sel", lambda: (st.delete_selected(), redraw())),
                       ("Jump sel", lambda: (st.jump_selected(), redraw())),
                       ("Wrap +1", lambda: (st.phase_wrap(1), redraw())),
                       ("Wrap -1", lambda: (st.phase_wrap(-1), redraw())),
                       ("Random models", do_random),
                       ("Edit par...", do_paredit),
                       ("Edit tim...", do_timedit)]:
        ttk.Button(bar, text=label, command=cmd).pack(side=tk.LEFT)

    # parameter fit checkboxes (state functions; first 14 fit on one row)
    parbar = ttk.Frame(root)
    parbar.pack(side=tk.BOTTOM, fill=tk.X)
    for p, isfit in st.fit_checkboxes()[:14]:
        var = tk.BooleanVar(value=isfit)

        def mk(pn, v):
            return lambda: st.set_fit(pn, v.get())

        ttk.Checkbutton(parbar, text=p, variable=var,
                        command=mk(p, var)).pack(side=tk.LEFT)

    redraw()
    root.mainloop()
