"""Tk plotting widget for interactive timing (reference ``pintk/plk.py``).

A compact Tk+matplotlib residual editor over :class:`pint_tpu.pintk.pulsar
.Pulsar`: residual plot with error bars, rectangle TOA selection, fit
button, parameter freeze/thaw checkboxes, phase-wrap and jump actions.
Imports of tkinter/matplotlib happen at call time so headless deployments
(and the --test CI path) never touch them.
"""

from __future__ import annotations

import numpy as np

__all__ = ["launch_gui"]


def launch_gui(psr):
    import tkinter as tk
    from tkinter import ttk

    import matplotlib

    matplotlib.use("TkAgg")
    from matplotlib.backends.backend_tkagg import FigureCanvasTkAgg
    from matplotlib.figure import Figure
    from matplotlib.widgets import RectangleSelector

    root = tk.Tk()
    root.title(f"pintk: {psr.name}")
    fig = Figure(figsize=(9, 5.5))
    ax = fig.add_subplot(111)
    canvas = FigureCanvasTkAgg(fig, master=root)
    canvas.get_tk_widget().pack(side=tk.TOP, fill=tk.BOTH, expand=1)
    state = {"selected": np.zeros(len(psr.all_toas), dtype=bool),
             "random_overlay": False, "colormode": "default"}

    def redraw():
        ax.clear()
        r = psr.resids()
        mjds = np.asarray(psr.all_toas.get_mjds(), dtype=float)
        res_us = np.asarray(r.time_resids) * 1e6
        errs = np.asarray(psr.all_toas.get_errors())
        if len(state["selected"]) != len(psr.all_toas):
            # tim edits change the TOA count; a stale mask kills every redraw
            state["selected"] = np.zeros(len(psr.all_toas), dtype=bool)
            state.pop("overlay_cache", None)
        sel = state["selected"]
        from pint_tpu.pintk.colormodes import get_color_mode

        groups = get_color_mode(state["colormode"]).get_groups(psr, sel)
        for lbl, col, m in groups:
            ax.errorbar(mjds[m], res_us[m], yerr=errs[m], fmt=".",
                        color=col, ecolor="0.8", label=lbl)
        if len(groups) > 1:
            ax.legend(loc="upper right", fontsize=7)
        if state["random_overlay"] and psr.fitted:
            # random-model overlay (reference pintk random models): draws
            # from the post-fit covariance shown as residual-delta curves.
            # Cached per fit: recomputing re-jits 12 model copies per click.
            try:
                if state.get("overlay_cache") is None:
                    state["overlay_cache"] = psr.random_models(
                        nmodels=12, keep_models=False)
                dphase = state["overlay_cache"]
                order = np.argsort(mjds)
                F0 = float(psr.model.F0.value)
                for k in range(dphase.shape[0]):
                    ax.plot(mjds[order], (res_us + dphase[k] / F0 * 1e6)[order],
                            color="#f0a030", alpha=0.35, lw=0.7, zorder=0)
            except Exception as e:
                from pint_tpu.logging import log

                log.warning(f"random-model overlay unavailable: {e}")
        ax.axhline(0, color="0.5", lw=0.8)
        ax.set_xlabel("MJD")
        ax.set_ylabel("Residual (us)")
        ax.set_title(f"{psr.name}  chi2={r.chi2:.2f}/{r.dof}")
        canvas.draw()

    def on_select(eclick, erelease):
        mjds = np.asarray(psr.all_toas.get_mjds(), dtype=float)
        res_us = np.asarray(psr.resids().time_resids) * 1e6
        x1, x2 = sorted([eclick.xdata, erelease.xdata])
        y1, y2 = sorted([eclick.ydata, erelease.ydata])
        state["selected"] |= ((mjds >= x1) & (mjds <= x2)
                              & (res_us >= y1) & (res_us <= y2))
        redraw()

    selector = RectangleSelector(ax, on_select, useblit=True, button=[1])

    bar = ttk.Frame(root)
    bar.pack(side=tk.BOTTOM, fill=tk.X)

    def do_fit():
        psr.fit()
        state.pop("overlay_cache", None)  # new covariance -> new draws
        redraw()

    def do_reset():
        psr.reset_model()
        state["selected"][:] = False
        redraw()

    def do_clear_sel():
        state["selected"][:] = False
        redraw()

    def do_jump():
        if state["selected"].any():
            psr.add_jump(state["selected"])
            redraw()

    def do_wrap(sign):
        if state["selected"].any():
            psr.add_phase_wrap(state["selected"], sign)
            redraw()

    def do_random():
        state["random_overlay"] = not state["random_overlay"]
        redraw()

    def do_paredit():
        from pint_tpu.pintk.paredit import ParChoiceWidget

        ParChoiceWidget(root, psr, updates_cb=redraw)

    def do_timedit():
        from pint_tpu.pintk.timedit import TimChoiceWidget

        TimChoiceWidget(root, psr, updates_cb=redraw)

    # color-mode selector (reference pintk colormodes)
    from pint_tpu.pintk.colormodes import COLOR_MODES

    ttk.Label(bar, text="Color:").pack(side=tk.RIGHT)
    mode_var = tk.StringVar(value="default")

    def on_mode(_ev=None):
        state["colormode"] = mode_var.get()
        redraw()

    combo = ttk.Combobox(bar, textvariable=mode_var, width=8,
                         values=sorted(COLOR_MODES), state="readonly")
    combo.bind("<<ComboboxSelected>>", on_mode)
    combo.pack(side=tk.RIGHT)

    for label, cmd in [("Fit", do_fit), ("Reset", do_reset),
                       ("Clear sel", do_clear_sel), ("Jump sel", do_jump),
                       ("Wrap +1", lambda: do_wrap(1)),
                       ("Wrap -1", lambda: do_wrap(-1)),
                       ("Random models", do_random),
                       ("Edit par...", do_paredit),
                       ("Edit tim...", do_timedit)]:
        ttk.Button(bar, text=label, command=cmd).pack(side=tk.LEFT)

    # parameter fit checkboxes
    parbar = ttk.Frame(root)
    parbar.pack(side=tk.BOTTOM, fill=tk.X)
    for p in psr.model.fittable_params[:14]:
        var = tk.BooleanVar(value=not getattr(psr.model, p).frozen)

        def mk(pn, v):
            return lambda: psr.set_fit_state(pn, v.get())

        ttk.Checkbutton(parbar, text=p, variable=var,
                        command=mk(p, var)).pack(side=tk.LEFT)

    redraw()
    root.mainloop()
