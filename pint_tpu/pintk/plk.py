"""Tk plotting widget for interactive timing (reference ``pintk/plk.py``).

A compact Tk+matplotlib residual editor over :class:`pint_tpu.pintk.pulsar
.Pulsar`: residual plot with error bars, rectangle TOA selection, fit
button, parameter freeze/thaw checkboxes, phase-wrap and jump actions.
Imports of tkinter/matplotlib happen at call time so headless deployments
(and the --test CI path) never touch them.
"""

from __future__ import annotations

import numpy as np

__all__ = ["launch_gui"]


def launch_gui(psr):
    import tkinter as tk
    from tkinter import ttk

    import matplotlib

    matplotlib.use("TkAgg")
    from matplotlib.backends.backend_tkagg import FigureCanvasTkAgg
    from matplotlib.figure import Figure
    from matplotlib.widgets import RectangleSelector

    root = tk.Tk()
    root.title(f"pintk: {psr.name}")
    fig = Figure(figsize=(9, 5.5))
    ax = fig.add_subplot(111)
    canvas = FigureCanvasTkAgg(fig, master=root)
    canvas.get_tk_widget().pack(side=tk.TOP, fill=tk.BOTH, expand=1)
    state = {"selected": np.zeros(len(psr.all_toas), dtype=bool)}

    def redraw():
        ax.clear()
        r = psr.resids()
        mjds = np.asarray(psr.all_toas.get_mjds(), dtype=float)
        res_us = np.asarray(r.time_resids) * 1e6
        errs = np.asarray(psr.all_toas.get_errors())
        sel = state["selected"]
        ax.errorbar(mjds[~sel], res_us[~sel], yerr=errs[~sel], fmt=".",
                    color="#2060a0", ecolor="0.8")
        if sel.any():
            ax.errorbar(mjds[sel], res_us[sel], yerr=errs[sel], fmt=".",
                        color="#d03020", ecolor="0.8")
        ax.axhline(0, color="0.5", lw=0.8)
        ax.set_xlabel("MJD")
        ax.set_ylabel("Residual (us)")
        ax.set_title(f"{psr.name}  chi2={r.chi2:.2f}/{r.dof}")
        canvas.draw()

    def on_select(eclick, erelease):
        mjds = np.asarray(psr.all_toas.get_mjds(), dtype=float)
        res_us = np.asarray(psr.resids().time_resids) * 1e6
        x1, x2 = sorted([eclick.xdata, erelease.xdata])
        y1, y2 = sorted([eclick.ydata, erelease.ydata])
        state["selected"] |= ((mjds >= x1) & (mjds <= x2)
                              & (res_us >= y1) & (res_us <= y2))
        redraw()

    selector = RectangleSelector(ax, on_select, useblit=True, button=[1])

    bar = ttk.Frame(root)
    bar.pack(side=tk.BOTTOM, fill=tk.X)

    def do_fit():
        psr.fit()
        redraw()

    def do_reset():
        psr.reset_model()
        state["selected"][:] = False
        redraw()

    def do_clear_sel():
        state["selected"][:] = False
        redraw()

    def do_jump():
        if state["selected"].any():
            psr.add_jump(state["selected"])
            redraw()

    def do_wrap(sign):
        if state["selected"].any():
            psr.add_phase_wrap(state["selected"], sign)
            redraw()

    for label, cmd in [("Fit", do_fit), ("Reset", do_reset),
                       ("Clear sel", do_clear_sel), ("Jump sel", do_jump),
                       ("Wrap +1", lambda: do_wrap(1)),
                       ("Wrap -1", lambda: do_wrap(-1))]:
        ttk.Button(bar, text=label, command=cmd).pack(side=tk.LEFT)

    # parameter fit checkboxes
    parbar = ttk.Frame(root)
    parbar.pack(side=tk.BOTTOM, fill=tk.X)
    for p in psr.model.fittable_params[:14]:
        var = tk.BooleanVar(value=not getattr(psr.model, p).frozen)

        def mk(pn, v):
            return lambda: psr.set_fit_state(pn, v.get())

        ttk.Checkbutton(parbar, text=p, variable=var,
                        command=mk(p, var)).pack(side=tk.LEFT)

    redraw()
    root.mainloop()
