"""Earth orientation: ITRF <-> GCRS rotation without ERFA.

Replaces the reference's ``erfautils.py:26 gcrs_posvel_from_itrf`` (pyerfa C)
with a native implementation: IAU 1976 precession + IAU 1980 nutation
(leading terms) + GMST/equation-of-equinoxes Earth rotation.  Polar motion
and UT1-UTC default to zero (no IERS feed in a zero-egress environment) but
are pluggable via :func:`set_eop_provider`; their omission contributes
< ~1.5 us of topocentric delay error, far below the analytic-ephemeris floor.

Truncation error of the nutation series is ~0.01 arcsec -> ~0.3 m at the
geocenter distance -> ~1 ns of timing, i.e. negligible.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "itrf_to_gcrs_matrix",
    "gcrs_posvel_from_itrf",
    "set_eop_provider",
]

_ARCSEC = np.pi / (180.0 * 3600.0)
_DEG = np.pi / 180.0
#: Earth rotation rate [rad/s] (IERS conventional)
OMEGA_EARTH = 7.292115146706979e-5


def _eop_zero(utc_mjd):
    """Default Earth-orientation parameters: (ut1_minus_utc_s, xp_rad, yp_rad)."""
    z = np.zeros_like(np.asarray(utc_mjd, dtype=np.float64))
    return z, z, z


_eop_provider = _eop_zero


def set_eop_provider(fn) -> None:
    """Install an IERS EOP provider: utc_mjd -> (UT1-UTC s, xp rad, yp rad)."""
    global _eop_provider
    _eop_provider = fn


def _R1(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack(
        [np.stack([o, z, z], -1), np.stack([z, c, s], -1), np.stack([z, -s, c], -1)], -2
    )


def _R2(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack(
        [np.stack([c, z, -s], -1), np.stack([z, o, z], -1), np.stack([s, z, c], -1)], -2
    )


def _R3(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack(
        [np.stack([c, s, z], -1), np.stack([-s, c, z], -1), np.stack([z, z, o], -1)], -2
    )


def _precession_matrix(T):
    """IAU 1976 precession: mean-of-date -> J2000 (T = TT Julian centuries)."""
    zeta = (2306.2181 * T + 0.30188 * T**2 + 0.017998 * T**3) * _ARCSEC
    z = (2306.2181 * T + 1.09468 * T**2 + 0.018203 * T**3) * _ARCSEC
    theta = (2004.3109 * T - 0.42665 * T**2 - 0.041833 * T**3) * _ARCSEC
    # P(J2000->date) = R3(-z) R2(theta) R3(-zeta); we need its inverse,
    # taking mean-of-date vectors to J2000: R3(zeta) R2(-theta) R3(z)
    return _R3(zeta) @ _R2(-theta) @ _R3(z)


# IAU 1980 nutation, leading terms.  Columns: multipliers of (l, l', F, D, Om),
# dpsi sin-coefficient [arcsec], deps cos-coefficient [arcsec].
_NUT_TERMS = np.array(
    [
        [0, 0, 0, 0, 1, -17.1996, 9.2025],
        [0, 0, 2, -2, 2, -1.3187, 0.5736],
        [0, 0, 2, 0, 2, -0.2274, 0.0977],
        [0, 0, 0, 0, 2, 0.2062, -0.0895],
        [0, 1, 0, 0, 0, 0.1426, 0.0054],
        [1, 0, 0, 0, 0, 0.0712, -0.0007],
        [0, 1, 2, -2, 2, -0.0517, 0.0224],
        [0, 0, 2, 0, 1, -0.0386, 0.0200],
        [1, 0, 2, 0, 2, -0.0301, 0.0129],
        [0, -1, 2, -2, 2, 0.0217, -0.0095],
        [1, 0, 0, -2, 0, -0.0158, -0.0001],
        [0, 0, 2, -2, 1, 0.0129, -0.0070],
        [-1, 0, 2, 0, 2, 0.0123, -0.0053],
        [0, 0, 0, 2, 0, 0.0063, -0.0002],
        [1, 0, 0, 0, 1, 0.0063, -0.0033],
        [-1, 0, 0, 0, 1, -0.0058, 0.0032],
        [-1, 0, 2, 2, 2, -0.0059, 0.0026],
        [1, 0, 2, 0, 1, -0.0051, 0.0027],
    ]
)


def _fundamental_args(T):
    """Delaunay arguments in radians (T = TT Julian centuries since J2000)."""
    l = (134.96298139 + 477198.8673981 * T) * _DEG  # noqa: E741
    lp = (357.52772333 + 35999.0503400 * T) * _DEG
    F = (93.27191028 + 483202.0175381 * T) * _DEG
    D = (297.85036306 + 445267.1114800 * T) * _DEG
    Om = (125.04452222 - 1934.1362608 * T) * _DEG
    return l, lp, F, D, Om


def _nutation_angles(T):
    """Return (dpsi, deps, eps0) in radians."""
    l, lp, F, D, Om = _fundamental_args(np.asarray(T))
    args = np.stack([l, lp, F, D, Om], axis=-1)  # (..., 5)
    mult = _NUT_TERMS[:, :5]  # (n, 5)
    phase = args @ mult.T  # (..., n)
    dpsi = np.sum(_NUT_TERMS[:, 5] * np.sin(phase), axis=-1) * _ARCSEC
    deps = np.sum(_NUT_TERMS[:, 6] * np.cos(phase), axis=-1) * _ARCSEC
    eps0 = (84381.448 - 46.8150 * T - 0.00059 * T**2 + 0.001813 * T**3) * _ARCSEC
    return dpsi, deps, eps0


def _gmst_rad(ut1_mjd):
    """Greenwich mean sidereal time (IAU 1982), radians."""
    ut1_mjd = np.asarray(ut1_mjd, dtype=np.float64)
    d0 = np.floor(ut1_mjd)
    frac = ut1_mjd - d0
    Tu = (d0 - 51544.5) / 36525.0
    gmst0 = 24110.54841 + 8640184.812866 * Tu + 0.093104 * Tu**2 - 6.2e-6 * Tu**3
    gmst_sec = gmst0 + frac * 86400.0 * 1.00273790934
    return (gmst_sec % 86400.0) / 86400.0 * 2.0 * np.pi


def itrf_to_gcrs_matrix(utc_mjd, tt_mjd=None):
    """Rotation matrix/matrices taking ITRF vectors to GCRS (J2000) frame."""
    utc_mjd = np.asarray(utc_mjd, dtype=np.float64)
    if tt_mjd is None:
        from pint_tpu.timescales import utc_to_tt_mjd

        tt_mjd = np.asarray(utc_to_tt_mjd(utc_mjd), dtype=np.float64)
    T = (np.asarray(tt_mjd, dtype=np.float64) - 51544.5) / 36525.0
    dut1, xp, yp = _eop_provider(utc_mjd)
    ut1_mjd = utc_mjd + dut1 / 86400.0
    dpsi, deps, eps0 = _nutation_angles(T)
    gast = _gmst_rad(ut1_mjd) + dpsi * np.cos(eps0)
    # nutation matrix: true-of-date -> mean-of-date
    N = _R1(-eps0) @ _R3(dpsi) @ _R1(eps0 + deps)
    P = _precession_matrix(T)
    # polar motion (xp, yp ~ 0 by default)
    W = _R2(xp) @ _R1(yp) if np.any(xp) or np.any(yp) else None
    R_earth = _R3(-gast)  # true-of-date <- pseudo-earth-fixed
    M = P @ N @ R_earth
    if W is not None:
        M = M @ W
    return M


def gcrs_posvel_from_itrf(itrf_xyz_m, utc_mjd, tt_mjd=None):
    """Observatory GCRS position [m] and velocity [m/s] from ITRF coordinates.

    The native stand-in for reference ``erfautils.py:26``.  Velocity is the
    Earth-rotation term (omega x r) rotated into GCRS; higher-order terms
    (precession/nutation rates) are < 1 mm/s and ignored.
    """
    itrf_xyz_m = np.asarray(itrf_xyz_m, dtype=np.float64)
    M = itrf_to_gcrs_matrix(utc_mjd, tt_mjd)  # (..., 3, 3)
    pos = (M @ itrf_xyz_m.reshape((3, 1))).reshape(M.shape[:-2] + (3,))
    omega = np.array([0.0, 0.0, OMEGA_EARTH])
    v_itrf_like = np.cross(omega, itrf_xyz_m)  # in the rotating sense
    vel = (M @ v_itrf_like.reshape((3, 1))).reshape(M.shape[:-2] + (3,))
    return pos, vel
