"""Chi2 over parameter grids — the reference's benchmark workload, TPU-style.

Counterpart of reference ``gridutils.py`` (``grid_chisq`` ``gridutils.py:164``,
``grid_chisq_derived`` ``gridutils.py:390``, ``tuple_chisq``
``gridutils.py:586``).  Where the reference pickles a fitter to a process pool
and re-runs the full Python design-matrix build per grid point (~20 s/point,
BASELINE.md), here one jitted function evaluates a *batch* of grid points:

* grid parameters are frozen per point, remaining free parameters are refit
  by a fixed-iteration Gauss-Newton loop **inside the trace**,
* ``vmap`` batches points; on a multi-device mesh the point axis is sharded
  with ``NamedSharding`` so XLA partitions the batch across chips (the
  reference's process-pool axis, SURVEY §2c mechanism 1).
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

__all__ = ["build_grid_chi2_fn", "grid_chisq", "grid_chisq_derived", "tuple_chisq"]

_warned_executor = False


def build_grid_chi2_fn(model, toas, grid_params: Sequence[str],
                       fit_params: Optional[Sequence[str]] = None,
                       niter: int = 4):
    """Return (fn, free_init) where fn(points (P, G)) -> chi2 (P,).

    ``fn`` refits ``fit_params`` at each grid point with ``niter`` Gauss-
    Newton steps (linearized WLS, mirroring one-shot-WLS-per-point semantics
    of the reference benchmark) and returns the resulting chi2 values.

    If the model carries correlated-noise components (ECORR / PL red noise)
    the per-point solve and chi2 switch to the GLS/Woodbury form
    automatically (reference ``gridutils.py`` runs whatever fitter class it
    was handed; ours dispatches on the noise structure).
    """
    if model.noise_basis_by_component(toas)[0]:
        return build_grid_gls_chi2_fn(model, toas, grid_params,
                                      fit_params=fit_params, niter=niter)
    grid_params = tuple(grid_params)
    if fit_params is None:
        fit_params = tuple(p for p in model.free_params if p not in grid_params)
    else:
        fit_params = tuple(fit_params)
    all_names = fit_params + grid_params
    c = model._get_compiled(toas, all_names)
    fns = model._cache["fns"][(all_names, len(toas))]
    eval_fn, jac_fn = fns["eval"], fns["jac_frac"]
    batch, ctx = c["batch"], c["ctx"]
    const_pv = model._const_pv()
    nfit = len(fit_params)
    F0 = float(model.F0.value)
    sigma = np.asarray(model.scaled_toa_uncertainty(toas))
    w = jnp.asarray(1.0 / sigma**2)
    free_init = jnp.array([float(getattr(model, p).value or 0.0) for p in all_names])

    # reference pulse numbers at the initial parameters (phase tracking)
    ph0, _ = eval_fn(free_init, const_pv, batch, ctx)
    int0 = ph0.int_

    # the jitted point-batch solver is cached on the model: all varying data
    # (parameter values, weights, batch, ctx) are traced ARGUMENTS, so
    # repeated grid_chisq calls — and the bench warmup — reuse one executable
    grid_key = ("grid_fn", all_names, nfit, niter, len(toas))
    if grid_key not in model._cache:

        def resid_cycles(values, const_pv, batch, ctx, int0, w):
            ph, _ = eval_fn(values, const_pv, batch, ctx)
            r = (ph.int_ - int0) + ph.frac
            return r - jnp.sum(r * w) / jnp.sum(w)  # Offset subtraction

        def chi2_point(gvals, free_init, const_pv, batch, ctx, int0, w, F0):
            v = jnp.concatenate([free_init[:nfit], gvals])
            ones = jnp.ones((len(w), 1))
            for _ in range(niter):
                r = resid_cycles(v, const_pv, batch, ctx, int0, w) / F0
                J = jac_fn(v, const_pv, batch, ctx)[:, :nfit]  # dfrac/dp
                M = -J / F0  # design matrix, seconds per unit param
                # explicit offset column: without it the step converges to a
                # stationary point of the UNPROFILED objective, not the joint
                # (offset, params) minimum the reference's Offset column finds
                A = jnp.concatenate([ones, M], axis=1)
                Aw = A * jnp.sqrt(w)[:, None]
                rw = r * jnp.sqrt(w)
                # normalized least squares for conditioning
                norms = jnp.linalg.norm(Aw, axis=0)
                norms = jnp.where(norms == 0, 1.0, norms)
                dpar, *_ = jnp.linalg.lstsq(Aw / norms, rw)
                v = v.at[:nfit].add(dpar[1:] / norms[1:])
            r = resid_cycles(v, const_pv, batch, ctx, int0, w) / F0
            return jnp.sum(w * r * r)

        # NOTE: the outer jit inlines the inner jitted eval/jac and lets XLA
        # re-optimize across the graph, which relaxes the dd error-free
        # transforms to ~1e-7 cycles (see bayesian.py _build_batch_fn).
        # For chi2 GRID SEARCH that is ~ns-level — far below TOA errors —
        # and the fused executable is what delivers the batched-fit
        # throughput, so the tradeoff goes the other way here.
        model._cache[grid_key] = jax.jit(jax.vmap(
            chi2_point, in_axes=(0, None, None, None, None, None, None, None)))
    vfn = model._cache[grid_key]

    def fn(points):
        return vfn(points, free_init, const_pv, batch, ctx, int0, w, F0)

    return fn, free_init


def build_grid_gls_chi2_fn(model, toas, grid_params: Sequence[str],
                           fit_params: Optional[Sequence[str]] = None,
                           niter: int = 4, chunk: int = 32):
    """GLS counterpart of :func:`build_grid_chi2_fn` for correlated-noise
    models (reference benchmark ``profiling/bench_chisq_grid.py`` semantics:
    a ``GLSFitter`` refit per grid point).

    Per point, each Gauss-Newton iteration solves the Woodbury-form
    augmented normal equations ``(A^T N^-1 A + diag(phiinv)) x = A^T N^-1 r``
    with ``A = [1 | M_timing | U_noise]`` (reference ``fitter.py:2712``) via
    Cholesky, then the final chi2 is ``r^T C^-1 r`` with
    ``C = diag(N) + U phi U^T`` (reference ``residuals.py:584`` →
    ``utils.py:3069``).  Points are processed in fixed-size chunks so one
    compiled executable covers any grid size with bounded memory.
    """
    grid_params = tuple(grid_params)
    if fit_params is None:
        fit_params = tuple(p for p in model.free_params if p not in grid_params)
    else:
        fit_params = tuple(fit_params)
    all_names = fit_params + grid_params
    model._get_compiled(toas, all_names)
    fns = model._cache["fns"][(all_names, len(toas))]
    eval_fn, jac_fn = fns["eval"], fns["jac_frac"]
    entry = model._cache["data"][toas]
    batch, ctx = entry[1], entry[2]
    const_pv = model._const_pv()
    nfit = len(fit_params)
    F0 = float(model.F0.value)
    sigma = np.asarray(model.scaled_toa_uncertainty(toas))
    w = jnp.asarray(1.0 / sigma**2)
    Us, ws, _ = model.noise_basis_by_component(toas)
    U = jnp.asarray(np.hstack(Us))
    phi = jnp.asarray(np.concatenate(ws))
    free_init = jnp.array([float(getattr(model, p).value or 0.0) for p in all_names])

    ph0, _ = eval_fn(free_init, const_pv, batch, ctx)
    int0 = ph0.int_

    grid_key = ("grid_gls_fn", all_names, nfit, niter, len(toas), chunk)
    if grid_key not in model._cache:

        def resid_seconds(values, const_pv, batch, ctx, int0, w, F0):
            ph, _ = eval_fn(values, const_pv, batch, ctx)
            r = (ph.int_ - int0) + ph.frac
            r = r - jnp.sum(r * w) / jnp.sum(w)
            return r / F0

        def chi2_point(gvals, free_init, const_pv, batch, ctx, int0, w,
                       U, phi, F0):
            from pint_tpu.utils import woodbury_dot

            v = jnp.concatenate([free_init[:nfit], gvals])
            ones = jnp.ones((U.shape[0], 1))
            for _ in range(niter):
                r = resid_seconds(v, const_pv, batch, ctx, int0, w, F0)
                J = jac_fn(v, const_pv, batch, ctx)[:, :nfit]
                M = -J / F0
                A = jnp.concatenate([ones, M, U], axis=1)
                norms = jnp.linalg.norm(A, axis=0)
                norms = jnp.where(norms == 0, 1.0, norms)
                A = A / norms
                phiinv = jnp.concatenate(
                    [jnp.full(1 + nfit, 1e-40), 1.0 / phi]) / norms**2
                mtcm = A.T @ (w[:, None] * A) + jnp.diag(phiinv)
                mtcy = A.T @ (w * r)
                L = jnp.linalg.cholesky(mtcm)
                x = jsl.cho_solve((L, True), mtcy)
                v = v.at[:nfit].add(x[1:1 + nfit] / norms[1:1 + nfit])
            r = resid_seconds(v, const_pv, batch, ctx, int0, w, F0)
            dot, _ = woodbury_dot(1.0 / w, U, phi, r, r)
            return dot

        model._cache[grid_key] = jax.jit(jax.vmap(
            chi2_point,
            in_axes=(0, None, None, None, None, None, None, None, None,
                     None)))
    vfn = model._cache[grid_key]

    def fn(points, sharding=None):
        points = jnp.asarray(points)
        npts = points.shape[0]
        blk_size = chunk
        if sharding is not None:
            # the fixed chunk must tile evenly onto the mesh axis
            ndev = sharding.mesh.devices.size
            blk_size = max(chunk, ndev) // ndev * ndev
        out = []
        for i in range(0, npts, blk_size):
            blk = points[i:i + blk_size]
            pad = blk_size - blk.shape[0]
            if pad:
                blk = jnp.concatenate([blk, jnp.tile(blk[-1:], (pad, 1))])
            if sharding is not None:
                blk = jax.device_put(blk, sharding)
            c2 = vfn(blk, free_init, const_pv, batch, ctx, int0, w, U,
                     phi, F0)
            out.append(c2[:blk_size - pad] if pad else c2)
        return jnp.concatenate(out)

    return fn, free_init


def grid_chisq(ftr, parnames: Sequence[str], parvalues: Sequence,
               executor=None, ncpu=None, chunksize=1, printprogress: bool = False,
               niter: int = 4, mesh=None, **fitargs) -> Tuple[np.ndarray, dict]:
    """Chi2 over an outer-product grid (reference ``gridutils.py:164`` API).

    ``executor``/``ncpu``/``chunksize`` are accepted for signature parity but
    are no-ops — points are batched on-device, which replaces the reference's
    process pool (warned once at runtime).  Pass ``mesh`` (a
    ``jax.sharding.Mesh`` with a 'grid' axis) to shard points across devices.
    """
    global _warned_executor
    if (executor is not None or ncpu not in (None, 1)) and not _warned_executor:
        from pint_tpu.logging import log

        _warned_executor = True
        log.warning("grid_chisq: executor/ncpu are no-ops here - grid points "
                    "are batched on-device (pass mesh= to use multiple "
                    "devices)")
    model, toas = ftr.model, ftr.toas
    parnames = tuple(parnames)
    grids = [np.asarray(v, dtype=np.float64) for v in parvalues]
    shape = tuple(len(g) for g in grids)
    mesh_pts = np.stack([g.ravel() for g in np.meshgrid(*grids, indexing="ij")], axis=-1)
    gls = bool(model.noise_basis_by_component(toas)[0])
    fn, _ = build_grid_chi2_fn(model, toas, parnames, niter=niter)
    pts = jnp.asarray(mesh_pts)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        if gls:
            # chunked path: each fixed-size chunk is sharded on entry
            chi2 = np.asarray(fn(pts, sharding=sharding))
        else:
            npts = pts.shape[0]
            ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
            pad = (-npts) % ndev
            if pad:
                pts = jnp.concatenate([pts, jnp.tile(pts[-1:], (pad, 1))])
            pts = jax.device_put(pts, sharding)
            chi2 = np.asarray(fn(pts))[:npts]
    else:
        chi2 = np.asarray(fn(pts))
    return chi2.reshape(shape), {}


def grid_chisq_derived(ftr, parnames: Sequence[str], parfuncs: Sequence,
                       gridvalues: Sequence, niter: int = 4,
                       **kw) -> Tuple[np.ndarray, list, dict]:
    """Grid over derived quantities: each model parameter in ``parnames`` is
    computed as ``parfuncs[i](*gridpoint)`` (reference ``gridutils.py:390``)."""
    model, toas = ftr.model, ftr.toas
    grids = [np.asarray(v, dtype=np.float64) for v in gridvalues]
    shape = tuple(len(g) for g in grids)
    mesh_arrays = np.meshgrid(*grids, indexing="ij")
    flat = [g.ravel() for g in mesh_arrays]
    pts = np.stack(
        [np.asarray([f(*vals) for vals in zip(*flat)], dtype=np.float64)
         for f in parfuncs], axis=-1)
    fn, _ = build_grid_chi2_fn(model, toas, tuple(parnames), niter=niter)
    chi2 = np.asarray(fn(jnp.asarray(pts)))
    out_grids = [g.reshape(shape) for g in mesh_arrays]
    return chi2.reshape(shape), out_grids, {}


def tuple_chisq(ftr, parnames: Sequence[str], parvalues: Sequence,
                niter: int = 4, **kw) -> Tuple[np.ndarray, dict]:
    """Chi2 at an explicit list of parameter tuples (reference
    ``gridutils.py:586``)."""
    model, toas = ftr.model, ftr.toas
    pts = jnp.asarray(np.asarray(parvalues, dtype=np.float64))
    fn, _ = build_grid_chi2_fn(model, toas, tuple(parnames), niter=niter)
    return np.asarray(fn(pts)), {}
