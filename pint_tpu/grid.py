"""Chi2 over parameter grids — the reference's benchmark workload, TPU-style.

Counterpart of reference ``gridutils.py`` (``grid_chisq`` ``gridutils.py:164``,
``grid_chisq_derived`` ``gridutils.py:390``, ``tuple_chisq``
``gridutils.py:586``).  Where the reference pickles a fitter to a process pool
and re-runs the full Python design-matrix build per grid point (~20 s/point,
BASELINE.md), here one jitted function evaluates a *batch* of grid points:

* grid parameters are frozen per point, remaining free parameters are refit
  by a fixed-iteration Gauss-Newton loop **inside the trace**,
* ``vmap`` batches points; on a multi-device mesh the point axis is sharded
  with ``NamedSharding`` so XLA partitions the batch across chips (the
  reference's process-pool axis, SURVEY §2c mechanism 1).
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from pint_tpu.exceptions import UsageError
from pint_tpu.runtime.solve import SVD_RUNG, hardened_cholesky
from pint_tpu.telemetry import jaxevents as _jaxevents
from pint_tpu.telemetry import span as _tspan

__all__ = ["build_grid_chi2_fn", "grid_chisq", "grid_chisq_derived",
           "tuple_chisq", "tuple_chisq_derived", "WrappedFitter", "doonefit",
           "hostinfo", "set_log"]

_warned_executor = False

# platform strings that mean "the TPU behind the tunnel" — the axon relay
# reports 'axon' in some environments and 'tpu' in others; chunk-size and
# ridge/normalization choices must agree for the same device, and the ONE
# definition lives with the preflight so its platform_matches verdict can
# never disagree with the grid's ridge selection
from pint_tpu.runtime.preflight import TPU_PLATFORMS as _TPU_PLATFORMS


def _model_param_sig(model) -> tuple:
    """Value signature of EVERY model parameter, mask selectors included:
    the invalidation key shared by the GLS bundle cache and the sweep
    checkpoint fingerprint.  Mask parameters (EFAC/ECORR/JUMP selectors)
    contribute their key/key_value because editing a selector's MJD range
    changes weights and noise bases at an unchanged parameter VALUE."""
    def sig(par, name):
        s = (name, str(par.value))
        if hasattr(par, "key"):
            s += (str(par.key), tuple(str(v) for v in par.key_value))
        return s

    return tuple(sig(c._params_dict[p], p)
                 for c in model.components.values() for p in c.params)


def hostinfo() -> str:
    """Host identification string for grid-run provenance (reference
    ``gridutils.py:26``)."""
    import platform

    return " ".join(platform.uname())


def set_log(logger_) -> None:
    """Swap the module logger (reference ``gridutils.py:30``, used by the
    reference to quiet pool workers; here a no-op hook kept for API
    parity — there are no worker processes to reconfigure)."""


class WrappedFitter:
    """Fitter wrapper that freezes chosen parameters at given values before
    fitting (reference ``gridutils.py:35``).  The on-device grid path
    (:func:`grid_chisq`) supersedes this for bulk grids; the wrapper remains
    for one-off frozen fits and API familiarity."""

    def __init__(self, ftr, **fitargs):
        self.ftr = ftr
        self.fitargs = fitargs

    def doonefit(self, parnames: Sequence[str], parvalues: Sequence[float],
                 extraparnames: Sequence[str] = ()) -> Tuple[float, list]:
        """Fit with ``parnames`` frozen at ``parvalues``; returns
        (chi2, extra parameter values)."""
        import copy

        model = copy.deepcopy(self.ftr.model)
        for name, value in zip(parnames, parvalues):
            getattr(model, name).value = float(value)
            getattr(model, name).frozen = True
        f = type(self.ftr)(self.ftr.toas, model)
        chi2 = float(f.fit_toas(**self.fitargs))
        extras = [getattr(f.model, n).value for n in extraparnames]
        return chi2, extras


def doonefit(ftr, parnames: Sequence[str], parvalues: Sequence[float],
             extraparnames: Sequence[str] = (),
             **fitargs) -> Tuple[float, list]:
    """One frozen-parameter fit (reference ``gridutils.py:112``)."""
    return WrappedFitter(ftr, **fitargs).doonefit(parnames, parvalues,
                                                  extraparnames)


def _classify_linear_columns(jac_fn, free_init, const_pv, batch, ctx,
                             nfit: int, ngrid: int,
                             grid_spans: Optional[Sequence[float]] = None):
    """Split fit-parameter design columns into (J0, nonlinear indices).

    Columns that stay put (rel < 1e-7) when every parameter moves by a
    ~1e-3-cycle phase step — and the grid axes sweep their span — are
    constant and can be hoisted out of the per-point trace.  The final chi2
    is exact regardless; only the Gauss-Newton trajectory is shaped by the
    split.
    """
    from pint_tpu.utils import classify_linear_columns, linearity_probe_steps

    J0_full = np.asarray(jac_fn(free_init, const_pv, batch, ctx))
    J0 = J0_full[:, :nfit]
    dp = linearity_probe_steps(J0_full)
    dp[~np.isfinite(dp)] = 0.0  # zero columns: no point perturbing
    for gi in range(ngrid):
        gv = float(np.asarray(free_init)[nfit + gi])
        span = 0.0
        if grid_spans is not None and gi < len(grid_spans):
            span = float(grid_spans[gi])
        if span <= 0.0:
            span = max(abs(gv) * 0.1, dp[nfit + gi])
        dp[nfit + gi] = span
    # bit-indexed sign probes: probe k flips the sign of parameter i iff
    # bit k of i is set, so every parameter PAIR differs in relative sign
    # in at least one probe — a column whose dependences on two parameters
    # cancel under one combined step cannot cancel in all probes, and
    # cancellation can't mask a nonlinear column.  ceil(log2(n))+1 extra
    # Jacobian evaluations, one-time cost at grid build.
    n = len(dp)
    nbits = max(1, int(np.ceil(np.log2(max(n, 2)))))
    idx = np.arange(n)
    nl: set = set()
    probed = np.zeros(ngrid)  # per-grid-axis span actually validated
    for k in range(nbits + 1):
        s = np.where((idx >> k) & 1, -1.0, 1.0) if k < nbits \
            else np.ones(n)
        # domain-aware probe: shrink a step that NaNs the Jacobian (e.g.
        # SINI pushed past 1) instead of letting non-finite columns force
        # everything nonlinear
        dp_eff = dp * s
        for _ in range(4):
            v_pert = np.asarray(free_init) + dp_eff
            J1 = np.asarray(jac_fn(jnp.asarray(v_pert), const_pv, batch,
                                   ctx))[:, :nfit]
            if np.all(np.isfinite(J1)):
                break
            dp_eff = dp_eff / 8.0
        probed = np.maximum(probed, np.abs(dp_eff[nfit:nfit + ngrid]))
        nl |= set(classify_linear_columns(J0, J1))
    nl_fit = sorted(nl)
    return J0, nl_fit, probed


def _classified_columns_cached(model, toas, jac_fn, free_init, const_pv,
                               batch, ctx, nfit: int, ngrid: int, grid_spans,
                               all_names) -> Tuple[np.ndarray, list]:
    """Classification result cached on the model so repeat ``grid_chisq``
    calls (and the bench's timed run after a full-span warmup) skip the
    ceil(log2 n)+2 probe Jacobian evaluations.

    Reuse requires (a) the same TOAs object, (b) the classification
    expansion point unchanged — a numerically probed 'constant' column is
    only known flat NEAR the probe point, so any parameter update forces a
    fresh probe — and (c) every grid axis within 2x the span it was
    classified at (beyond that a column that looked constant may go
    nonlinear, so reclassify at the larger span).
    """
    # _version is NOT part of the key: in-place TOA mutation at unchanged
    # length (pintk edits) must force a fresh probe (J0 was evaluated on
    # the pre-mutation data), but keying on the version would grow a new
    # ~MB-scale Jacobian entry per edit.  The version lives in the cached
    # VALUE and is compared alongside the expansion point, so edits
    # overwrite the single entry instead of leaking (ADVICE.md round 5).
    key = ("grid_classify", all_names, nfit, toas)
    version = getattr(toas, "_version", 0)
    spans = tuple(float(s) for s in (grid_spans if grid_spans is not None
                                     else ()))
    fi = np.asarray(free_init)
    cached = model._cache.get(key)
    if cached is not None:
        c_spans, c_fi, J0, nl_fit, c_version = cached
        if (c_version == version and np.array_equal(c_fi, fi)
                and len(c_spans) == len(spans)
                and all(s <= 2.0 * cs for s, cs in zip(spans, c_spans))):
            return J0, nl_fit
        if len(c_spans) == len(spans):
            spans = tuple(max(s, cs) for s, cs in zip(spans, c_spans))
    J0, nl_fit, probed = _classify_linear_columns(
        jac_fn, free_init, const_pv, batch, ctx, nfit, ngrid,
        spans if spans else None)
    # cache the span each axis was ACTUALLY validated over — a
    # domain-shrunk probe must not be credited with the requested span
    model._cache[key] = (tuple(float(p) for p in probed), fi, J0, nl_fit,
                         version)
    return J0, nl_fit


def _free_init_of(model, all_names) -> np.ndarray:
    """Initial free-parameter vector in builder name order.  The single
    spelling shared by both grid builders and the elastic fingerprint
    primer — the checkpoint fingerprint hashes this array, so a drift
    between a builder's copy and the primer's would break cross-rung
    resume with a spurious CheckpointError."""
    return np.array([float(getattr(model, p).value or 0.0)
                     for p in all_names], dtype=np.float64)


def build_grid_chi2_fn(model, toas, grid_params: Sequence[str],
                       fit_params: Optional[Sequence[str]] = None,
                       niter: int = 4,
                       grid_spans: Optional[Sequence[float]] = None,
                       chunk: Optional[int] = None):
    """Return (fn, free_init, fit_params) where
    ``fn(points (P, G)) -> (chi2 (P,), vfit (P, nfit))``.

    ``fn`` refits ``fit_params`` at each grid point with ``niter`` Gauss-
    Newton steps (linearized WLS, mirroring one-shot-WLS-per-point semantics
    of the reference benchmark) and returns the resulting chi2 values plus
    the converged fit-parameter values (column i = ``fit_params[i]``, for
    ``extraparnames``).

    If the model carries correlated-noise components (ECORR / PL red noise)
    the per-point solve and chi2 switch to the GLS/Woodbury form
    automatically (reference ``gridutils.py`` runs whatever fitter class it
    was handed; ours dispatches on the noise structure).
    """
    if model.noise_basis_by_component(toas)[0]:
        kw = {} if chunk is None else {"chunk": chunk}
        return build_grid_gls_chi2_fn(model, toas, grid_params,
                                      fit_params=fit_params, niter=niter,
                                      grid_spans=grid_spans, **kw)
    grid_params = tuple(grid_params)
    if fit_params is None:
        fit_params = tuple(p for p in model.free_params if p not in grid_params)
    else:
        fit_params = tuple(fit_params)
    all_names = fit_params + grid_params
    c = model._get_compiled(toas, all_names)
    fns = model._cache["fns"][(all_names, len(toas))]
    eval_fn, jac_fn = fns["eval"], fns["jac_frac"]
    batch, ctx = c["batch"], c["ctx"]
    const_pv = model._const_pv()
    nfit = len(fit_params)
    F0 = float(model.F0.value)
    sigma = np.asarray(model.scaled_toa_uncertainty(toas))
    w = jnp.asarray(1.0 / sigma**2)
    free_init = jnp.asarray(_free_init_of(model, all_names))

    # reference pulse numbers at the initial parameters (phase tracking)
    ph0, _ = eval_fn(free_init, const_pv, batch, ctx)
    int0 = ph0.int_

    # constant design columns hoisted out of the trace (same machinery as
    # the GLS path; see _classify_linear_columns)
    J0, nl_fit = _classified_columns_cached(
        model, toas, jac_fn, free_init, const_pv, batch, ctx, nfit,
        len(grid_params), grid_spans, all_names)
    Jbase = jnp.asarray(J0)

    # the jitted point-batch solver is cached on the model: all varying data
    # (parameter values, weights, batch, ctx) are traced ARGUMENTS, so
    # repeated grid_chisq calls — and the bench warmup — reuse one executable
    grid_key = ("grid_fn", all_names, nfit, niter, len(toas), tuple(nl_fit))
    if grid_key not in model._cache:
        nl_idx = jnp.asarray(nl_fit, dtype=jnp.int32)

        def resid_cycles(values, const_pv, batch, ctx, int0, w):
            ph, _ = eval_fn(values, const_pv, batch, ctx)
            r = (ph.int_ - int0) + ph.frac
            return r - jnp.sum(r * w) / jnp.sum(w)  # Offset subtraction

        def chi2_point(gvals, free_init, const_pv, batch, ctx, int0, w, F0,
                       Jbase):
            v0 = jnp.concatenate([free_init[:nfit], gvals])
            ones = jnp.ones((len(w), 1), dtype=jnp.float64)

            # one Gauss-Newton iteration; rolled into a lax.scan so the
            # (large) phase-evaluation graph is compiled ONCE, not niter
            # times — same math, ~niter-times-smaller executable
            def gn_step(v, _):
                r = resid_cycles(v, const_pv, batch, ctx, int0, w) / F0
                if len(nl_fit):
                    def frac_of(sub):
                        ph, _ = eval_fn(v.at[nl_idx].set(sub), const_pv,
                                        batch, ctx)
                        return ph.frac
                    Jnl = jax.jacfwd(frac_of)(v[nl_idx])
                    J = Jbase.at[:, nl_idx].set(Jnl)
                else:
                    J = Jbase
                M = -J / F0  # design matrix, seconds per unit param
                # explicit offset column: without it the step converges to a
                # stationary point of the UNPROFILED objective, not the joint
                # (offset, params) minimum the reference's Offset column finds
                A = jnp.concatenate([ones, M], axis=1)
                Aw = A * jnp.sqrt(w)[:, None]
                rw = r * jnp.sqrt(w)
                # normalized least squares for conditioning; lstsq is
                # SVD-based, i.e. already the ladder's final rung — its
                # singular values feed the per-point diagnostics for free
                norms = jnp.linalg.norm(Aw, axis=0)
                norms = jnp.where(norms == 0, 1.0, norms)
                dpar, _, _, sv = jnp.linalg.lstsq(Aw / norms, rw)
                ok = jnp.all(jnp.isfinite(sv))
                cond = jnp.max(sv) / jnp.maximum(jnp.min(sv), 1e-300)
                lvl = jnp.where(ok, jnp.int32(SVD_RUNG), jnp.int32(-1))
                cond = jnp.where(ok, cond, jnp.nan)
                return v.at[:nfit].add(dpar[1:] / norms[1:]), (lvl, cond)

            v, (lvls, conds) = jax.lax.scan(gn_step, v0, None, length=niter)
            r = resid_cycles(v, const_pv, batch, ctx, int0, w) / F0
            lvl_worst = jnp.where(jnp.any(lvls < 0), jnp.int32(-1),
                                  jnp.max(lvls))
            diag = jnp.stack([lvl_worst.astype(jnp.float64),
                              jnp.zeros((), dtype=jnp.float64),
                              jnp.max(conds)])
            # the refit parameter values ride along for extraparnames
            # (reference gridutils.py:116-160 extraout)
            return jnp.sum(w * r * r), v[:nfit], diag

        # NOTE: the outer jit inlines the inner jitted eval/jac and lets XLA
        # re-optimize across the graph, which relaxes the dd error-free
        # transforms to ~1e-7 cycles (see bayesian.py _build_batch_fn).
        # For chi2 GRID SEARCH that is ~ns-level — far below TOA errors —
        # and the fused executable is what delivers the batched-fit
        # throughput, so the tradeoff goes the other way here.
        model._cache[grid_key] = jax.jit(jax.vmap(
            chi2_point,
            in_axes=(0, None, None, None, None, None, None, None, None)))
    vfn = model._cache[grid_key]

    _last_pts: list = []

    def fn(points):
        """(chi2 (P,), vfit (P, nfit), diag (P, 3)) — diag columns are
        (ladder rung, ridge applied, condition estimate) per point."""
        _last_pts[:] = [points]
        return vfn(points, free_init, const_pv, batch, ctx, int0, w, F0,
                   Jbase)

    def analysis_handle():
        """(jitted fn, example args) of the executable the last call ran
        — the AOT cost-attribution hook (telemetry.costs); None before
        any evaluation."""
        if not _last_pts:
            return None
        return vfn, (_last_pts[0], free_init, const_pv, batch, ctx, int0,
                     w, F0, Jbase)

    fn.analysis_handle = analysis_handle
    return fn, free_init, fit_params


#: static per-backend chunk defaults — the floor the autotuner must
#: beat.  TPU: measured round 5 on a real v5e (tools/tpu_sweep.py,
#: B1855 grid; fits/s): at 256 points chunk 64/128/256/512 gave
#: 96.3/101.5/106.9/49.6, at 1024 points 167.4/172.2/160.4/143.7 — 128
#: is at or near the top at both scales, while 256 wins only when the
#: grid is exactly one chunk and 512 halves the 256-point rate by
#: padding (before the no-materialized-B kernel, chunk >= 256 did not
#: compile at all: scoped-vmem OOM).  CPU: the r4/r5 sweeps favor 128
#: when isolated — same value, independently measured, kept as its own
#: row so a backend whose sweep disagrees changes one entry.  Unknown
#: backends take the CPU row (the conservative host-style default).
_STATIC_CHUNK = {"tpu": 128, "cpu": 128}


def default_gls_chunk(backend=None) -> int:
    """Static batch size for the chunked GLS grid executable on
    ``backend`` (default: the executing backend).

    Resolution order: the process override
    (:func:`pint_tpu.config.set_grid_chunk` / ``PINT_TPU_GRID_CHUNK``;
    typed :class:`~pint_tpu.exceptions.UsageError` on non-positive or
    non-integer values) wins, else the measured per-backend default
    (:data:`_STATIC_CHUNK`).  This is the *static fallback* the
    autotuner's tuned decisions must beat — ``grid_chisq(chunk="auto")``
    consults :func:`pint_tpu.autotune.resolve_grid_chunk`, which
    degrades here on any manifest/fingerprint miss.  Callers with a
    fixed, known grid size can pass ``chunk=`` to match it (as bench.py
    does with 256 for its 256-point headline).
    """
    from pint_tpu import config as _config

    override = _config.grid_chunk()
    if override is not None:
        return int(override)
    if backend is None:
        backend = jax.default_backend()
    if backend in _TPU_PLATFORMS:
        backend = "tpu"
    return _STATIC_CHUNK.get(backend, _STATIC_CHUNK["cpu"])


def _resolve_auto_chunk(model, toas, chunk, gls: bool = True):
    """The ONE spelling of the ``chunk`` string contract shared by
    ``grid_chisq`` and ``build_grid_gls_chi2_fn``: ``"auto"`` resolves
    the autotuner's tuned decision (static default + reasoned
    ``tune_fallback`` event on any manifest miss), any other string is
    a typed error, and non-strings pass through untouched.  On a
    non-GLS workload ``"auto"`` resolves to ``None`` — there is no
    chunked executable to tune."""
    if not isinstance(chunk, str):
        return chunk
    if chunk != "auto":
        raise UsageError(
            f"chunk={chunk!r}: pass a positive integer, 'auto', or "
            "None for the static default")
    if not gls:
        return None
    from pint_tpu import autotune as _autotune

    return _autotune.resolve_grid_chunk(model, toas)


def build_grid_gls_chi2_fn(model, toas, grid_params: Sequence[str],
                           fit_params: Optional[Sequence[str]] = None,
                           niter: int = 4, chunk=None,
                           grid_spans: Optional[Sequence[float]] = None,
                           correction_dtype: Optional[str] = None,
                           precision=None):
    """GLS counterpart of :func:`build_grid_chi2_fn` for correlated-noise
    models (reference benchmark ``profiling/bench_chisq_grid.py`` semantics:
    a ``GLSFitter`` refit per grid point).

    Per point, each Gauss-Newton iteration solves the Woodbury-form
    augmented normal equations ``(A^T N^-1 A + diag(phiinv)) x = A^T N^-1 r``
    with ``A = [1 | M_timing | U_noise]`` (reference ``fitter.py:2712``) via
    Cholesky, then the final chi2 is ``r^T C^-1 r`` with
    ``C = diag(N) + U phi U^T`` (reference ``residuals.py:584`` →
    ``utils.py:3069``).  Points are processed in fixed-size chunks so one
    compiled executable covers any grid size with bounded memory; the
    default chunk is the backend's measured static value
    (:func:`default_gls_chunk`), overridable per call for a known grid
    size; ``chunk="auto"`` asks the autotuner for the tuned decision
    (:func:`pint_tpu.autotune.resolve_grid_chunk` — manifest miss
    degrades to the static default with a reasoned telemetry event).

    ``correction_dtype`` selects the precision of the Woodbury
    chi2-correction segment (``"float64"`` | ``"float32"``); ``None``
    consults the precision layer's override policy first (the
    ``grid.correction`` segment), then the autotuner's dd-split-guarded
    probe decision, which keeps float64 unless measured safe for
    exactly this system.

    ``precision`` is the ``grid.gram`` segment's
    :class:`~pint_tpu.precision.SegmentSpec` — the per-point
    design/Gram products inside the traced kernel run at its compute
    dtype with its accumulation back to f64.  ``None`` resolves the
    active policy (override -> manifest ``precision.grid.gram`` key ->
    f64 default); an f64 spec is bit-identical to the pre-precision
    kernel.
    """
    from pint_tpu import precision as _precision

    chunk = _resolve_auto_chunk(model, toas, chunk)
    if chunk is None:
        chunk = default_gls_chunk()
    if isinstance(chunk, bool) or not isinstance(chunk, (int, np.integer)) \
            or int(chunk) <= 0:
        raise UsageError(
            f"chunk must be a positive integer or 'auto', got {chunk!r}")
    chunk = int(chunk)
    if correction_dtype is None:
        corr_override = _precision.override_spec("grid.correction")
        if corr_override is not None:
            correction_dtype = "float32" if corr_override.reduced \
                else "float64"
        else:
            from pint_tpu import autotune as _autotune

            correction_dtype = _autotune.resolve_correction_dtype(model,
                                                                  toas)
    if correction_dtype not in ("float64", "float32"):
        raise UsageError(
            f"correction_dtype must be 'float64' or 'float32', got "
            f"{correction_dtype!r}")
    if precision is None:
        precision = _precision.segment_spec("grid.gram", model=model,
                                            toas=toas)
    elif not isinstance(precision, _precision.SegmentSpec):
        raise UsageError(
            f"precision must be a SegmentSpec or None, got "
            f"{type(precision).__name__}")
    grid_params = tuple(grid_params)
    if fit_params is None:
        fit_params = tuple(p for p in model.free_params if p not in grid_params)
    else:
        fit_params = tuple(fit_params)
    all_names = fit_params + grid_params
    model._get_compiled(toas, all_names)
    fns = model._cache["fns"][(all_names, len(toas))]
    eval_fn, jac_fn = fns["eval"], fns["jac_frac"]
    entry = model._cache["data"][toas]
    batch, ctx = entry[1], entry[2]
    const_pv = model._const_pv()
    nfit = len(fit_params)
    F0 = float(model.F0.value)
    # --- hoisted per-grid constants, cached by parameter values -----------
    # Everything in this block is a pure function of (model parameter
    # values, TOAs version, names, niter, spans).  Repeated grid_chisq
    # calls at unchanged values — bench's warm->timed pairing, pintk
    # re-grids, random-model overlays — reuse the device-resident bundle
    # and skip both the Gram/Cholesky host work and ~45 MB of
    # host->device transfers: the round-5 device trace put this rebuild
    # at ~1 s of a 2.5 s 256-point-grid call.  ONE slot only, overwritten
    # when values change, so fit loops cannot accumulate device memory.
    import weakref

    # the TOAs take part by IDENTITY (weakref, compared with `is`): two
    # TOAs objects of equal length and _version are still different data,
    # and every other cache here (data entries, classification, noise
    # bases) is keyed per-object too.  niter is deliberately absent —
    # nothing in the bundle depends on it (it only keys the executable).
    # Parameter values AND mask selectors key the bundle (_model_param_sig):
    # editing an EFAC/ECORR selector's MJD range changes the noise bases
    # and weights at an unchanged parameter VALUE and must invalidate the
    # cached Gram/Cholesky.  nfit pins the fit/grid split: two calls with
    # coinciding all_names but different partitions hoist different J0
    # columns and must not collide.
    vkey = (_model_param_sig(model),
            getattr(toas, "_version", 0), all_names, nfit, len(toas),
            None if grid_spans is None else tuple(grid_spans))
    slot = model._cache.get("grid_gls_bundle")
    if slot is not None and slot[0] == vkey and slot[1]() is toas:
        (free_init, int0, w, nl_fit, B_base, A_base, Y_base, U_w, L_D,
         s_col, U_chi, cf_chi) = slot[2]
    else:
        sigma = np.asarray(model.scaled_toa_uncertainty(toas))
        W_np = 1.0 / sigma**2
        w = jnp.asarray(W_np)
        Us, ws, _ = model.noise_basis_by_component(toas)
        U_np = np.hstack(Us)
        phi_np = np.concatenate(ws)
        free_init = jnp.asarray(_free_init_of(model, all_names))

        ph0, _ = eval_fn(free_init, const_pv, batch, ctx)
        int0 = ph0.int_

        # (1) Linear-parameter Jacobian columns.  Most fit parameters (DMX
        #     bins, jumps, FD, DM Taylor terms) enter the phase linearly, so
        #     their design-matrix columns are CONSTANT; only genuinely
        #     nonlinear parameters (spin, astrometry, binary) need
        #     re-deriving per iteration.  Classify numerically: perturb
        #     every parameter (and the grid values) and keep columns that
        #     move.  The final chi2 is exact either way — the split only
        #     shapes the Gauss-Newton trajectory, and nonlinear columns are
        #     still recomputed exactly.
        J0, nl_fit = _classified_columns_cached(
            model, toas, jac_fn, free_init, const_pv, batch, ctx, nfit,
            len(grid_params), grid_spans, all_names)
        # (2) Noise-basis blocks of the normal equations and the Woodbury
        #     Cholesky for the final chi2: U, phi, and the weights never
        #     change, so U^T W U and chol(diag(1/phi) + U^T N^-1 U) are
        #     per-grid constants (reference recomputes both per point,
        #     ``fitter.py:2712``, ``utils.py:3069``).
        UtWU_np = U_np.T @ (W_np[:, None] * U_np)
        # final-chi2 basis: offset marginalized exactly as
        # Residuals.calc_chi2 — the grid's chi2 must be definitionally
        # identical to the fitter's
        U_chi_np, phi_chi = model.augment_basis_for_offset(U_np, phi_np,
                                                           n=len(toas))
        Sigma_chi = np.diag(1.0 / phi_chi) \
            + U_chi_np.T @ (W_np[:, None] * U_chi_np)
        # hardened: a near-singular noise Gram (Coles et al. correlated-
        # noise regime) gets escalating diagonal loading instead of an
        # opaque LinAlgError; total failure raises typed errors
        cf_chi_np, jit_chi, _ = hardened_cholesky(
            Sigma_chi, name="grid Woodbury chi2 Gram")
        cf_chi = jnp.asarray(cf_chi_np)
        U_chi = jnp.asarray(U_chi_np)

        # --- Schur-complement solve constants ----------------------------
        # The augmented normal matrix is [[A, C], [C^T, D]] with a timing
        # block A (1+nfit)^2, coupling C, and noise block
        # D = diag(1/phi) + U^T W U.  D is GRID-CONSTANT: prefactor L_D
        # once, and per point solve only the marginalized timing system
        # (A - C D^-1 C^T) x_t = b_t - C D^-1 b_u.  Only the ~|nl|
        # nonlinear design columns of B change per iteration, so
        # B/A/C/Y = L_D^-1 C^T are hoisted with just those rows/cols
        # refreshed — the per-fit cost drops from an O((nt+nu)^3) dense
        # Cholesky plus full O(n*nt*nu) Gram matmuls to nonlinear-row
        # matmuls, a k-column triangular solve, and an O(nt^3) Cholesky.
        # The Gauss-Newton step is algebraically identical; the final chi2
        # (below) is computed independently either way.
        M0 = -np.asarray(J0) / F0
        B_base_np = np.hstack([np.ones((len(toas), 1)), M0])
        # unit-W-norm column scaling (the fitter's normalize_designmatrix
        # move, reference ``fitter.py:2712``): raw Gram entries reach ~1e42
        # (F1^T W F1 at 4005 TOAs), beyond the TPU's emulated-f64 dynamic
        # range — an f64 is stored as a float32 pair, so anything past
        # ~3.4e38 lands on the device as inf and NaN-poisons every grid
        # point (r04 all-NaN grid).  With the scales hoisted here (f64 host
        # arithmetic), every device-side matrix stays O(1); the solve is
        # algebraically unchanged and steps are de-scaled on the way out.
        s_col_np = np.sqrt((W_np[:, None] * B_base_np**2).sum(axis=0))
        s_col_np = np.where(s_col_np > 0, s_col_np, 1.0)
        B_base_np = B_base_np / s_col_np
        U_w_np = W_np[:, None] * U_np
        A_base_np = B_base_np.T @ (W_np[:, None] * B_base_np)
        C_base_np = B_base_np.T @ U_w_np
        L_D_np, jit_D, _ = hardened_cholesky(
            np.diag(1.0 / phi_np) + UtWU_np, name="grid noise block")
        if max(jit_chi, jit_D) > 0:
            from pint_tpu.logging import log

            log.warning(
                f"grid GLS bundle: noise Gram needed diagonal loading "
                f"(chi2 {jit_chi:.2e}, solve {jit_D:.2e}) — near-singular "
                "correlated-noise model")
        import scipy.linalg as _sl

        Y_base_np = _sl.solve_triangular(L_D_np, C_base_np.T, lower=True)
        B_base = jnp.asarray(B_base_np)
        A_base = jnp.asarray(A_base_np)
        Y_base = jnp.asarray(Y_base_np)
        U_w = jnp.asarray(U_w_np)
        L_D = jnp.asarray(L_D_np)
        s_col = jnp.asarray(s_col_np)
        model._cache["grid_gls_bundle"] = (vkey, weakref.ref(toas), (
            free_init, int0, w, nl_fit, B_base, A_base, Y_base, U_w, L_D,
            s_col, U_chi, cf_chi))
    nl_all = nl_fit  # positions within the full value vector == fit positions

    # reduced-precision segment (autotune decision grid.correction_dtype,
    # dd-split-guarded): the Woodbury chi2-correction operands are cast
    # ONCE here — the cached bundle stays f64, so flipping the decision
    # never poisons the full-precision path — and the kernel computes
    # the z = L^-1 (U^T W r) correction in that dtype, casting the
    # scalar back to f64 for the subtraction.  float64 (the default,
    # and the probe's outcome on every realistic workload) is the
    # bit-identical pre-autotune path.
    _f32_corr = correction_dtype == "float32"
    if _f32_corr:
        U_chi = _precision.downcast(U_chi, "float32")
        cf_chi = _precision.downcast(cf_chi, "float32")

    # Solve recipe for the marginalized (Schur) timing system, fixed at
    # trace time per backend.  CPU: normalize by diag(A - Y^T Y) with a
    # 1e-12 ridge — keeps degenerate-direction refit values in lockstep
    # with the scalar doonefit path (test_grid extraparnames parity).
    # TPU: the emulated ~49-bit f64 can cancel noise-absorbed Schur pivots
    # negative (r04 bench: 1/an^2 of a 1e-300-clamped pivot overflowed and
    # the Cholesky went NaN), so normalize by the UNmarginalized diag(A),
    # which is positive by construction; the matmul error is then bounded
    # at ~sqrt(n)*2^-49 ~ 1e-13 of the normalized scale and a 1e-9 ridge
    # guarantees positive definiteness.  Absorbed directions get
    # Levenberg-damped toward the initial values — the final chi2 is
    # computed independently of step quality either way.
    _TPU = jax.default_backend() in _TPU_PLATFORMS
    _RIDGE = 1e-9 if _TPU else 1e-12

    # grid.gram precision segment: the spec is trace-time static —
    # closed over the kernel and part of the executable key below.  The
    # f64 default short-circuits _pm to the plain `a @ b` the
    # pre-precision kernel ran (bit-identical).
    _gram_spec = precision if precision.reduced else None
    _pm = _precision.matmul

    # correction_dtype + the gram-spec key sit BEFORE the nl tuple: the
    # classification result stays the key's last element (tests
    # introspect it there)
    grid_key = ("grid_gls_fn", all_names, nfit, niter, len(toas), chunk,
                correction_dtype, precision.key(), tuple(nl_fit))
    if grid_key not in model._cache:
        nl_idx = jnp.asarray(nl_all, dtype=jnp.int32)
        # positions of the nonlinear columns within B (offset col 0 shifts)
        nlp_idx = jnp.asarray([1 + i for i in nl_all], dtype=jnp.int32)

        def resid_seconds(values, const_pv, batch, ctx, int0, w, F0):
            ph, _ = eval_fn(values, const_pv, batch, ctx)
            r = (ph.int_ - int0) + ph.frac
            r = r - jnp.sum(r * w) / jnp.sum(w)
            return r / F0

        # s_col is a traced argument, NOT a closure constant: the cached
        # executable is reused across grid_chisq calls (the key ignores
        # parameter values), so every weight-dependent hoisted array must
        # flow in as data or a rebuilt fn would de-scale with a stale copy
        def chi2_point(gvals, free_init, const_pv, batch, ctx, int0, w,
                       F0, B_base, A_base, Y_base, U_w, L_D,
                       U_chi, cf_chi, s_col, ridge_scale):
            v0 = jnp.concatenate([free_init[:nfit], gvals])
            nt = 1 + nfit

            # one Gauss-Newton iteration; rolled into a lax.scan so the
            # phase-evaluation + jacfwd graph (which dwarfs everything
            # else) is compiled ONCE, not niter times
            def gn_step(v, _):
                r = resid_seconds(v, const_pv, batch, ctx, int0, w, F0)
                wr = w * r
                if len(nl_all):
                    def frac_of(sub):
                        ph, _ = eval_fn(v.at[nl_idx].set(sub), const_pv,
                                        batch, ctx)
                        return ph.frac
                    Jnl = jax.jacfwd(frac_of)(v[nl_idx])
                    # same unit-W-norm column scale as the hoisted bases
                    M_nl = (-Jnl / F0) / s_col[nlp_idx]  # (n, k)
                    # The per-point design matrix B = B_base with columns
                    # nlp_idx <- M_nl is NEVER materialized: under vmap
                    # that .set was a (chunk, n, nt) scatter — the kernel's
                    # scoped-vmem ceiling on v5e (chunk >= 256 OOMed) and a
                    # full per-point copy of the mostly-constant basis.  B
                    # only ever appears as B^T @ x, which equals
                    # B_base^T @ x with the k rows at nlp_idx replaced by
                    # M_nl^T @ x — an O(nt*k) fix-up, and B_base stays a
                    # broadcast constant the batched matmul can share.
                    wM = w[:, None] * M_nl
                    A_cols = _pm(B_base.T, wM, _gram_spec) \
                        .at[nlp_idx, :].set(_pm(M_nl.T, wM, _gram_spec))
                    # refresh the nl rows/cols of the Gram blocks: the
                    # (nl, nl) sub-block is written consistently twice
                    A = A_base.at[:, nlp_idx].set(A_cols)
                    A = A.at[nlp_idx, :].set(A_cols.T)
                    C_rows = _pm(M_nl.T, U_w, _gram_spec)  # (k, nu)
                    Y_cols = jsl.solve_triangular(L_D, C_rows.T, lower=True)
                    Y = Y_base.at[:, nlp_idx].set(Y_cols)
                    b_t = _pm(B_base.T, wr, _gram_spec) \
                        .at[nlp_idx].set(_pm(M_nl.T, wr, _gram_spec))
                else:
                    A, Y = A_base, Y_base
                    b_t = _pm(B_base.T, wr, _gram_spec)
                b_u = _pm(U_w.T, r, _gram_spec)
                z_u = jsl.solve_triangular(L_D, b_u, lower=True)
                Ar = A - Y.T @ Y
                rhs = b_t - Y.T @ z_u
                if _TPU:
                    dA = jnp.diag(A)
                    an = jnp.sqrt(jnp.maximum(dA, 1e-30 * jnp.max(dA)))
                else:
                    an = jnp.sqrt(jnp.maximum(jnp.diag(Ar), 1e-300))
                # hardened solve, escalation-pass variant: ONE Cholesky
                # at _RIDGE * ridge_scale — at scale 1 this is exactly
                # the pre-guardrail solve (bit-identical, zero overhead;
                # a fully on-trace multi-rung ladder measured ~8x the
                # batch solve cost, far past the 10%-of-throughput
                # budget).  A failed point is POISONED (NaN step -> NaN
                # chi2, never fabricated) and flagged; the chunk driver
                # below re-runs only affected chunks at escalated scales
                # — host decisions happen at chunk granularity, never
                # inside this vmapped body.
                Arn = Ar / jnp.outer(an, an) \
                    + (_RIDGE * ridge_scale) * jnp.eye(nt, dtype=jnp.float64)
                L = jnp.linalg.cholesky(Arn)
                x = jsl.cho_solve((L, True), rhs / an) / an
                ok = jnp.all(jnp.isfinite(x))
                x = jnp.where(ok, x, jnp.nan)
                dL = jnp.diagonal(L)
                # condition proxy from the factor (exact cond needs an
                # eigensolve, which is what blew the budget)
                cond = (jnp.max(dL) / jnp.maximum(jnp.min(dL),
                                                  1e-300)) ** 2
                return v.at[:nfit].add((x / s_col)[1:nt]), (ok, cond)

            v, (oks, conds) = jax.lax.scan(gn_step, v0, None,
                                           length=niter)
            r = resid_seconds(v, const_pv, batch, ctx, int0, w, F0)
            # chi2 = r^T C^-1 r via Woodbury with the prefactored Sigma;
            # the correction segment runs in the tuned dtype (operands
            # pre-cast above) and its scalar is cast back to f64
            wr = w * r
            if _f32_corr:
                z = jsl.solve_triangular(
                    cf_chi,
                    U_chi.T @ _precision.downcast(wr, "float32"),
                    lower=True)
            else:
                z = jsl.solve_triangular(cf_chi, U_chi.T @ wr, lower=True)
            # per-point diagnostics for THIS pass: solved flag (every GN
            # iteration factored) and worst condition proxy
            diag = jnp.stack([jnp.where(jnp.all(oks), 1.0, 0.0),
                              jnp.max(conds)])
            corr = (z @ z).astype(jnp.float64)
            return jnp.sum(r * wr) - corr, v[:nfit], diag

        model._cache[grid_key] = jax.jit(jax.vmap(
            chi2_point,
            in_axes=(0, None, None, None, None, None, None, None, None,
                     None, None, None, None, None, None, None, None)))
    vfn = model._cache[grid_key]

    #: ridge multipliers for the chunk-level escalation ladder (rung i
    #: solves at _RIDGE * _ESCALATION[i])
    _ESCALATION = (1.0, 1e3, 1e6)

    _last_blk: list = []
    #: dispatches of the last fn/fused call (the dispatch-amortization
    #: counter tests/test_workperbyte asserts on)
    _dispatches: list = [0]

    def _eval_chunk(blk, scale):
        _last_blk[:] = [blk]
        _dispatches[0] += 1
        return vfn(blk, free_init, const_pv, batch, ctx, int0, w, F0,
                   B_base, A_base, Y_base, U_w, L_D, U_chi, cf_chi,
                   s_col, jnp.float64(scale))

    def _fused_vfn(fuse: int):
        """ONE jitted scan-over-chunks executable retiring ``fuse``
        chunk blocks per dispatch (cached in the model cache next to
        the chunk executable, so repeat sweeps and elastic rungs hit
        the warm cache — zero steady-state recompiles)."""
        fkey = grid_key + ("fused", int(fuse))
        if fkey not in model._cache:
            def scan_chunks(blocks, free_init, const_pv, batch, ctx,
                            int0, w, F0, B_base, A_base, Y_base, U_w,
                            L_D, U_chi, cf_chi, s_col, scale):
                def step(_, blk):
                    return (), vfn(blk, free_init, const_pv, batch,
                                   ctx, int0, w, F0, B_base, A_base,
                                   Y_base, U_w, L_D, U_chi, cf_chi,
                                   s_col, scale)

                _, ys = jax.lax.scan(step, (), blocks)
                return ys

            model._cache[fkey] = jax.jit(scan_chunks)
        return model._cache[fkey]

    def _eval_fused(blocks, scale, fuse):
        """Dispatch ONE scan executable over ``blocks`` (fuse, B, G)."""
        _dispatches[0] += 1
        return _fused_vfn(fuse)(
            blocks, free_init, const_pv, batch, ctx, int0, w, F0,
            B_base, A_base, Y_base, U_w, L_D, U_chi, cf_chi, s_col,
            jnp.float64(scale))

    def fn(points, sharding=None):
        """(chi2 (P,), vfit (P, nfit), diag (P, 3)) — diag columns are
        (ladder rung, ridge applied, condition estimate) per point.

        Escalation runs at CHUNK granularity: pass 0 dispatches every
        chunk at the base ridge before any host sync (async pipelining
        preserved); only chunks reporting an unsolved point re-run at
        escalated ridges, and only the failed points take the escalated
        values.  Healthy sweeps therefore cost exactly the pre-guardrail
        solve.  Points no rung solves keep NaN chi2 with rung -1 — loud,
        never fabricated."""
        _dispatches[0] = 0
        points = jnp.asarray(points)
        npts = points.shape[0]
        blk_size = chunk
        if sharding is not None:
            # the fixed chunk must tile evenly onto the mesh axis
            ndev = sharding.mesh.devices.size
            blk_size = max(chunk, ndev) // ndev * ndev
        blks, keeps = [], []
        for i in range(0, npts, blk_size):
            blk = points[i:i + blk_size]
            pad = blk_size - blk.shape[0]
            if pad:
                blk = jnp.concatenate([blk, jnp.tile(blk[-1:], (pad, 1))])
            if sharding is not None:
                blk = jax.device_put(blk, sharding)
            blks.append(blk)
            keeps.append(blk_size - pad)
        first = [_eval_chunk(b, 1.0) for b in blks]
        out, out_v, out_d = [], [], []
        for blk, keep, (c2, vf, dg) in zip(blks, keeps, first):
            c2, vf, dg = _escalate_chunk(blk, keep, c2, vf, dg)
            out.append(c2)
            out_v.append(vf)
            out_d.append(dg)
        return (np.concatenate(out), np.concatenate(out_v),
                np.concatenate(out_d))

    def _escalate_chunk(blk, keep, c2, vf, dg):
        """Shared chunk-level escalation tail: re-run ONLY chunks that
        report unsolved points at escalated ridges; failed points take
        escalated values, the rest keep the base pass.  ``blk`` may be
        a zero-arg callable (lazy device placement — the healthy path
        never pays the transfer).  Returns (chi2, vfit,
        diag-with-rung-columns) for one chunk."""
        c2 = np.array(np.asarray(c2)[:keep])
        vf = np.array(np.asarray(vf)[:keep])
        dg = np.asarray(dg)[:keep]
        solved = dg[:, 0] > 0.5
        cond = np.array(dg[:, 1])
        rung = np.where(solved, 0, -1)
        for ri in range(1, len(_ESCALATION)):
            if solved.all():
                break
            if callable(blk):
                blk = blk()
            c2e, vfe, dge = (np.asarray(a)[:keep] for a in
                             _eval_chunk(blk, _ESCALATION[ri]))
            newly = ~solved & (dge[:, 0] > 0.5)
            c2[newly] = c2e[newly]
            vf[newly] = vfe[newly]
            cond[newly] = dge[newly, 1]
            rung[newly] = ri
            solved |= newly
        if not solved.all():
            from pint_tpu.logging import log

            log.warning(
                f"grid GLS solve: {int((~solved).sum())} point(s) "
                "unsolved at every escalation ridge — their chi2 is "
                "NaN (rung -1), not fabricated")
        ridge = np.where(
            rung >= 0,
            _RIDGE * np.take(np.asarray(_ESCALATION),
                             np.maximum(rung, 0)), np.nan)
        return c2, vf, np.stack([rung.astype(np.float64), ridge, cond],
                                axis=1)

    def fused(points, sharding=None, fuse: int = 8):
        """Scan-fused sweep: ``fuse`` chunk blocks retired per dispatch
        through ONE ``lax.scan``-over-chunks executable (same chunk
        kernel, same results — the scanned body IS ``vfn``), so the
        per-dispatch overhead that dominates small shards is paid
        ``ceil(nchunks/fuse)`` times instead of ``nchunks``.  The last
        group pads by repeating its final block (one executable shape
        per (fuse, chunk) pair — no steady-state recompiles).
        Escalation stays at chunk granularity on the rare failed
        chunks, exactly like :func:`fn`."""
        _dispatches[0] = 0
        fuse = max(1, int(fuse))
        points = jnp.asarray(points)
        npts = points.shape[0]
        blk_size = chunk
        if sharding is not None:
            ndev = sharding.mesh.devices.size
            blk_size = max(chunk, ndev) // ndev * ndev
        blks, keeps = [], []
        for i in range(0, npts, blk_size):
            blk = points[i:i + blk_size]
            pad = blk_size - blk.shape[0]
            if pad:
                blk = jnp.concatenate([blk, jnp.tile(blk[-1:], (pad, 1))])
            blks.append(blk)
            keeps.append(blk_size - pad)
        group_sharding = None
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            group_sharding = NamedSharding(
                sharding.mesh, P(None, *sharding.spec))
        out, out_v, out_d = [], [], []
        for lo in range(0, len(blks), fuse):
            group = blks[lo:lo + fuse]
            gkeeps = keeps[lo:lo + fuse]
            real = len(group)
            while len(group) < fuse:          # constant executable shape
                group.append(group[-1])
            blocks = jnp.stack(group)
            if group_sharding is not None:
                blocks = jax.device_put(blocks, group_sharding)
            c2g, vfg, dgg = _eval_fused(blocks, 1.0, fuse)
            c2g, vfg, dgg = (np.asarray(a) for a in (c2g, vfg, dgg))
            for f in range(real):
                def _blk(i=lo + f):
                    return blks[i] if sharding is None \
                        else jax.device_put(blks[i], sharding)

                c2, vf, dg = _escalate_chunk(_blk, gkeeps[f], c2g[f],
                                             vfg[f], dgg[f])
                out.append(c2)
                out_v.append(vf)
                out_d.append(dg)
        return (np.concatenate(out), np.concatenate(out_v),
                np.concatenate(out_d))

    def fused_eval(fuse: int, sharding=None):
        """Per-rung fused evaluator for the elastic supervisor: a host
        callable taking stacked blocks ``(fuse, B, G)`` and returning
        ``{"chi2": (fuse, B), "vfit": ..., "diag": ...}`` from ONE
        scan dispatch, with the chunk-level escalation tail applied per
        block (same 3-column rung/ridge/condition diagnostics as the
        unfused elastic evaluator)."""
        group_sharding = None
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            group_sharding = NamedSharding(
                sharding.mesh, P(None, *sharding.spec))

        def ev(blocks):
            blocks = jnp.asarray(blocks)
            if group_sharding is not None:
                blocks = jax.device_put(blocks, group_sharding)
            c2g, vfg, dgg = (np.asarray(a) for a in
                             _eval_fused(blocks, 1.0, int(fuse)))
            B = int(blocks.shape[1])
            c2o, vfo, dgo = [], [], []
            for f in range(int(blocks.shape[0])):
                def _blk(i=f):
                    b = blocks[i]
                    return b if sharding is None \
                        else jax.device_put(b, sharding)

                c2, vf, dg = _escalate_chunk(_blk, B, c2g[f], vfg[f],
                                             dgg[f])
                c2o.append(c2)
                vfo.append(vf)
                dgo.append(dg)
            return {"chi2": np.stack(c2o), "vfit": np.stack(vfo),
                    "diag": np.stack(dgo)}

        return ev

    def analysis_handle():
        """(jitted fn, example args) of the chunk executable the last
        call dispatched — sharded blocks keep their sharding, so cost
        analysis sees the same per-device program the sweep ran; None
        before any evaluation."""
        if not _last_blk:
            return None
        return vfn, (_last_blk[0], free_init, const_pv, batch, ctx, int0,
                     w, F0, B_base, A_base, Y_base, U_w, L_D, U_chi,
                     cf_chi, s_col, jnp.float64(1.0))

    def cost_handle(points, sharding=None):
        """(jitted fn, example args) for the chunk executable at these
        points WITHOUT dispatching anything — the autotuner's AOT
        analysis hook.  The first chunk-shaped block is built exactly
        as :func:`fn` would (same padding, same sharding placement), so
        the analyzed executable IS the one a sweep would run."""
        points = jnp.asarray(points)
        blk_size = chunk
        if sharding is not None:
            ndev = sharding.mesh.devices.size
            blk_size = max(chunk, ndev) // ndev * ndev
        blk = points[:blk_size]
        pad = blk_size - blk.shape[0]
        if pad:
            blk = jnp.concatenate([blk, jnp.tile(blk[-1:], (pad, 1))])
        if sharding is not None:
            blk = jax.device_put(blk, sharding)
        return vfn, (blk, free_init, const_pv, batch, ctx, int0, w, F0,
                     B_base, A_base, Y_base, U_w, L_D, U_chi, cf_chi,
                     s_col, jnp.float64(1.0))

    fn.analysis_handle = analysis_handle
    fn.cost_handle = cost_handle
    fn.fused = fused
    fn.fused_eval = fused_eval
    fn.dispatch_count = lambda: _dispatches[0]
    return fn, free_init, fit_params


def _extraout(extraparnames, fit_params, grid_params, vfit, pts, model,
              shape=None):
    """Per-point refit parameter values (reference ``gridutils.py:116-160``
    ``extraout``): refit params come from the converged Gauss-Newton state,
    grid params from the grid point itself, anything else is the model's
    (constant) current value."""
    out = {}
    if not extraparnames:
        return out
    vf = np.asarray(vfit)  # one device->host gather for all names
    pts = np.asarray(pts)
    fit_params, grid_params = list(fit_params), list(grid_params)
    for name in extraparnames:
        if name in fit_params:
            col = vf[:, fit_params.index(name)]
        elif name in grid_params:
            col = pts[:, grid_params.index(name)]
        else:
            col = np.full(len(vf), float(getattr(model, name).value or 0.0))
        out[name] = col.reshape(shape) if shape is not None else col
    return out


def _attach_grid_executable(ftr, fn, model=None) -> None:
    """Record the evaluated grid executable on the fitter
    (``ftr.last_grid_executable`` = (jitted fn, example args)) for AOT
    cost attribution, and — in full telemetry mode — analyze it once per
    executable and stream the profile as span attrs + a ``cost_profile``
    runlog record.  The analysis result is cached per executable on the
    model so repeat sweeps (and the escalation ladder's re-runs) never
    pay a second lower/compile.

    The FIRST analysis is a real XLA compile (AOT ``lower().compile()``
    does not consult jit's dispatch cache) with the jaxevents accounting
    paused; on a TPU backend that costs ~the grid compile itself (~28 s
    on the B1855 workload) unless a persistent compilation cache can
    serve it, so the automatic full-mode analysis is SKIPPED on TPU
    platforms without one — explicit ``costs.profile_grid(ftr)`` calls
    (bench.py, which configures the cache) remain available."""
    handle = getattr(fn, "analysis_handle", None)
    got = handle() if handle is not None else None
    if got is None:
        return
    ftr.last_grid_executable = got
    from pint_tpu import config as _config

    if _config._telemetry_mode != "full":
        return
    if jax.default_backend() in _TPU_PLATFORMS and not getattr(
            jax.config, "jax_compilation_cache_dir", None):
        from pint_tpu.logging import log

        log.info("grid cost attribution skipped: TPU backend without a "
                 "persistent compilation cache — the analysis compile "
                 "would cost ~a full grid compile (call "
                 "telemetry.costs.profile_grid(ftr) explicitly to pay it)")
        return
    try:
        from pint_tpu.telemetry import costs as _costs
        from pint_tpu.telemetry import distview as _distview

        vfn = got[0]
        cache = model._cache.setdefault("grid_cost_profiles", {}) \
            if model is not None else {}
        cached = cache.get(id(vfn))
        if cached is None:
            # ONE AOT compile serves all three analyses (shared
            # compiled-executable cache in telemetry.costs).  vfn
            # itself is stored in the value so the id() key cannot be
            # recycled by a later executable while the entry lives —
            # a freed address re-used by a NEW chunk fn would
            # otherwise serve the OLD executable's documents
            cached = (
                vfn,
                _costs.analyze_jitted(vfn, *got[1], name="grid.chunk"),
                _distview.analyze_jitted_collectives(
                    vfn, *got[1], name="grid.chunk"),
                _distview.sharding_plan_of_jitted(
                    vfn, *got[1], name="grid.chunk"),
            )
            cache[id(vfn)] = cached
        _, prof, coll, plan = cached
        _costs.record_cost_profile(prof)
        _distview.record_collective_profile(coll)
        _distview.record_sharding_plan(plan)
    except Exception as e:  # attribution must never take the sweep down
        from pint_tpu.logging import log

        log.warning(f"grid cost attribution failed "
                    f"({type(e).__name__}: {e}); sweep results unaffected")


def _attach_grid_diagnostics(ftr, diag, shape=None):
    """Stash the per-point solve diagnostics (and the device profile) on
    the fitter: ``ftr.last_grid_diagnostics`` maps ``ladder_rung`` /
    ``ridge`` / ``condition`` to grid-shaped arrays.  Rung -1 flags a
    poisoned (non-finite) point; rung ``SVD_RUNG`` the pseudo-inverse.

    With telemetry on, the per-point diagnostics are summarized onto the
    current span as a ``grid.solve`` event (rung histogram, worst
    condition) — the structured-run-log form of the same information."""
    from pint_tpu.runtime.preflight import device_profile

    d = np.asarray(diag)
    out = {"ladder_rung": d[:, 0].astype(int), "ridge": d[:, 1],
           "condition": d[:, 2]}
    if shape is not None:
        out = {k: v.reshape(shape) for k, v in out.items()}
    out["device_profile"] = device_profile()
    ftr.last_grid_diagnostics = out
    from pint_tpu import config as _config

    if _config._telemetry_mode != "off" and d.size:
        from pint_tpu.telemetry import event as _tevent

        rungs = d[:, 0].astype(int)
        cond = d[:, 2]
        finite = np.isfinite(cond)
        _tevent("grid.solve", points=int(len(rungs)),
                unsolved=int(np.sum(rungs < 0)),
                escalated=int(np.sum(rungs > 0)),
                worst_condition=float(cond[finite].max()) if finite.any()
                else None,
                rung_histogram=str({int(r): int(n) for r, n in
                                    zip(*np.unique(rungs,
                                                   return_counts=True))}))
    return out


def grid_chisq(ftr, parnames: Sequence[str], parvalues: Sequence,
               extraparnames: Sequence[str] = (),
               executor=None, ncpu=None, chunksize=1, printprogress: bool = False,
               niter: int = 4, mesh=None, chunk=None,
               checkpoint: Optional[str] = None, retry=None,
               plan=None, fuse: Optional[int] = None,
               **fitargs) -> Tuple[np.ndarray, dict]:
    """Chi2 over an outer-product grid (reference ``gridutils.py:164`` API).

    ``executor``/``ncpu``/``chunksize`` are accepted for signature parity but
    are no-ops — points are batched on-device, which replaces the reference's
    process pool (warned once at runtime).  Pass ``mesh`` (a
    ``jax.sharding.Mesh`` with a 'grid' axis) to shard points across devices;
    ``chunk`` overrides the GLS path's fixed executable batch size
    (default: the backend's static value, :func:`default_gls_chunk`,
    itself overridable via ``PINT_TPU_GRID_CHUNK``; the
    tools/tpu_sweep.py knob).  ``chunk="auto"`` loads the autotuner's
    tuned decision for this workload shape + device fingerprint
    (:mod:`pint_tpu.autotune`) and degrades to the static default — with
    a reasoned ``tune_fallback`` telemetry event — on any manifest miss.
    ``extraparnames`` returns the per-point refit values of those parameters
    in the second return slot, shaped like the grid.

    ``plan`` routes the sweep through the execution-plan layer:
    ``"auto"`` selects a plan from the preflight-certified device set
    (:func:`pint_tpu.runtime.plan.select_plan`), or pass an
    :class:`~pint_tpu.runtime.plan.ExecutionPlan` directly.  Combined
    with ``checkpoint``, the sweep runs under the **elastic supervisor**
    (:mod:`pint_tpu.runtime.elastic`): per-chunk persistence, a
    cross-replica canary on every block, and — on device loss, canary
    mismatch, or collective failure — eviction of the bad device, mesh
    degradation down the 8→4→2→1 ladder, and resume from the last
    checkpoint.  The elastic report lands on ``ftr.last_elastic_report``.

    ``checkpoint`` (without a plan) names a directory: the sweep runs
    through the chunked executor (:mod:`pint_tpu.runtime.checkpoint`) —
    completed chunks persist to disk, failed chunks retry with
    exponential backoff (``retry``, a
    :class:`~pint_tpu.runtime.checkpoint.RetryPolicy`), and a crashed
    sweep resumes from the last completed chunk.  Per-point solve
    diagnostics land on ``ftr.last_grid_diagnostics`` either way.

    ``fuse`` (GLS path) batches that many chunk blocks into ONE
    ``lax.scan``-over-chunks executable per dispatch — the
    work-per-byte dispatch-amortization knob (ROADMAP item 2: the
    scaling series' small shards were dispatch-floor-bound).  Results
    are identical to the unfused path (the scanned body IS the chunk
    kernel); dispatches drop ``fuse``-fold.  Composes with ``plan`` +
    ``checkpoint``: the elastic supervisor dispatches fused groups
    while checkpoint chunks stay logical, so degradation/resume
    semantics are unchanged.
    """
    global _warned_executor
    if (executor is not None or ncpu not in (None, 1)) and not _warned_executor:
        from pint_tpu.logging import log

        _warned_executor = True
        log.warning("grid_chisq: executor/ncpu are no-ops here - grid points "
                    "are batched on-device (pass mesh= to use multiple "
                    "devices)")
    from pint_tpu.runtime.preflight import check_device

    check_device()
    model, toas = ftr.model, ftr.toas
    parnames = tuple(parnames)
    grids = [np.asarray(v, dtype=np.float64) for v in parvalues]
    shape = tuple(len(g) for g in grids)
    mesh_pts = np.stack([g.ravel() for g in np.meshgrid(*grids, indexing="ij")], axis=-1)
    gls = bool(model.noise_basis_by_component(toas)[0])
    # resolve "auto" ONCE up front (the chunk also sizes checkpoint
    # blocks and elastic logical chunks below)
    chunk = _resolve_auto_chunk(model, toas, chunk, gls=gls)
    if plan is not None:
        if mesh is not None:
            raise UsageError("plan= and mesh= cannot be combined; the plan "
                             "carries its own mesh")
        if isinstance(plan, str):
            from pint_tpu.runtime.plan import select_plan

            if plan != "auto":
                raise UsageError(f"plan={plan!r}: pass 'auto' or an "
                                 "ExecutionPlan")
            plan = select_plan("grid", n_items=int(mesh_pts.shape[0]))
    with _tspan("grid_chisq", npts=int(mesh_pts.shape[0]), gls=gls,
                niter=niter, params=",".join(parnames),
                checkpointed=checkpoint is not None) as sp, \
            _jaxevents.watch(sp):
        if checkpoint is not None and plan is not None:
            # elastic path: logical chunking + canary + degradation;
            # builds its own per-rung executables (the chunk size folds
            # in the canary rows), so the shared build below is skipped
            chi2, vfit, diag, fit_params = _elastic_grid(
                ftr, model, toas, parnames, mesh_pts, niter, gls,
                chunk, checkpoint, retry, plan, fuse=fuse)
            _attach_grid_diagnostics(ftr, diag, shape=shape)
            extraout = _extraout(extraparnames, fit_params, parnames,
                                 vfit, mesh_pts, model, shape=shape)
            return np.asarray(chi2).reshape(shape), extraout
        with _tspan("grid.build_fn"):
            fn, free_init, fit_params = build_grid_chi2_fn(
                model, toas, parnames, niter=niter,
                grid_spans=_point_spans(model, parnames, mesh_pts),
                chunk=chunk)
        if checkpoint is not None:
            if mesh is not None:
                raise UsageError("checkpoint= and mesh= cannot be combined; "
                                 "pass plan= for elastic checkpointed "
                                 "multi-device execution")
            if fuse is not None and int(fuse) > 1:
                # the plain chunked executor has no fused dispatch path;
                # silently ignoring the knob would let a caller believe
                # dispatches dropped fuse-fold when nothing changed
                raise UsageError(
                    "fuse= with checkpoint= needs plan= (the elastic "
                    "supervisor owns fused checkpointed dispatch); drop "
                    "fuse or add plan='auto'")
            from pint_tpu.runtime.preflight import device_profile

            # the fingerprint must cover everything the chi2 surface depends
            # on — grid definition, EVERY parameter value/selector, and the
            # TOA data version — or a resume would silently stitch chunks
            # from different data into one surface.  Mesh/device identity
            # is deliberately NOT hashed: it rides in the sidecar, so the
            # same sweep resumes across device counts.
            chi2, vfit, diag = _checkpointed_grid(
                fn, mesh_pts, checkpoint, retry,
                fingerprint=_grid_fingerprint(parnames, mesh_pts, niter,
                                              toas, gls, model, free_init),
                chunk=chunk if chunk else (default_gls_chunk() if gls
                                           else 256),
                sidecar={"platform": device_profile().platform,
                         "num_devices": device_profile().num_devices})
        elif mesh is not None or (plan is not None
                                  and plan.mesh is not None):
            if mesh is None:
                mesh = plan.mesh
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
            if gls:
                # chunked path: each fixed-size chunk is sharded on
                # entry; fuse>1 retires that many chunks per dispatch
                if fuse is not None and int(fuse) > 1:
                    chi2, vfit, diag = fn.fused(jnp.asarray(mesh_pts),
                                                sharding=sharding,
                                                fuse=int(fuse))
                else:
                    chi2, vfit, diag = fn(jnp.asarray(mesh_pts),
                                          sharding=sharding)
            else:
                pts = jnp.asarray(mesh_pts)
                npts = pts.shape[0]
                ndev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
                pad = (-npts) % ndev
                if pad:
                    pts = jnp.concatenate([pts, jnp.tile(pts[-1:],
                                                         (pad, 1))])
                pts = jax.device_put(pts, sharding)
                chi2, vfit, diag = fn(pts)
                chi2, vfit, diag = chi2[:npts], vfit[:npts], diag[:npts]
        elif fuse is not None and int(fuse) > 1 and gls:
            with _tspan("grid.evaluate") as esp:
                chi2, vfit, diag = esp.sync(
                    fn.fused(jnp.asarray(mesh_pts), fuse=int(fuse)))
        else:
            with _tspan("grid.evaluate") as esp:
                chi2, vfit, diag = esp.sync(fn(jnp.asarray(mesh_pts)))
        chi2, vfit, diag = (np.asarray(chi2), np.asarray(vfit),
                            np.asarray(diag))
        from pint_tpu import config as _config

        if _config._telemetry_mode != "off":
            # account the device->host result gather (np.asarray has no
            # central hook — see telemetry.jaxevents); full mode also
            # samples the live-buffer watermark at the sweep's peak
            _jaxevents.record_transfer(
                "d2h", chi2.nbytes + vfit.nbytes + diag.nbytes, count=1)
            if _config._telemetry_mode == "full":
                _jaxevents.memory_snapshot()
        _attach_grid_executable(ftr, fn, model=model)
        _attach_grid_diagnostics(ftr, diag, shape=shape)
        extraout = _extraout(extraparnames, fit_params, parnames, vfit,
                             mesh_pts, model, shape=shape)
        return chi2.reshape(shape), extraout


def _grid_fingerprint(parnames, mesh_pts, niter, toas, gls, model,
                      free_init) -> dict:
    """The sweep-identity fingerprint shared by the plain-checkpointed
    and elastic grid paths.  Everything the chi2 surface depends on is
    here; mesh/device identity deliberately is NOT (it lives in the
    checkpoint sidecar), so a sweep checkpointed on 8 devices resumes
    on 4 with the same fingerprint."""
    return dict(parnames=parnames, pts=mesh_pts, niter=niter,
                ntoas=len(toas), gls=gls,
                toas_version=getattr(toas, "_version", 0),
                params=_model_param_sig(model),
                free_init=np.asarray(free_init))


def _checkpointed_grid(fn, mesh_pts: np.ndarray, checkpoint: str, retry,
                       fingerprint: dict, chunk: int, sidecar=None):
    """Run the grid through the chunked checkpointed executor; chunks are
    contiguous point blocks so a resumed sweep re-evaluates the same
    blocks through the same compiled executable (chi2 surface identical
    to an uninterrupted run)."""
    from pint_tpu.runtime.checkpoint import checkpointed_map

    blocks = [mesh_pts[i:i + chunk] for i in range(0, len(mesh_pts), chunk)]

    def chunk_fn(blk):
        c2, vf, dg = fn(jnp.asarray(blk))
        return {"chi2": np.asarray(c2), "vfit": np.asarray(vf),
                "diag": np.asarray(dg)}

    outs = checkpointed_map(chunk_fn, blocks, checkpoint=checkpoint,
                            fingerprint=fingerprint, retry=retry,
                            sidecar=sidecar)
    return (np.concatenate([o["chi2"] for o in outs]),
            np.concatenate([o["vfit"] for o in outs]),
            np.concatenate([o["diag"] for o in outs]))


def _elastic_grid(ftr, model, toas, parnames, mesh_pts, niter, gls,
                  chunk, checkpoint, retry, plan, fuse=None):
    """Route the grid sweep through the elastic supervisor: logical
    (device-count-independent) chunks, a cross-replica canary per block,
    device eviction + mesh degradation on failure, resume from the
    checkpoint.  ``fuse`` > 1 dispatches that many logical chunks per
    scan-fused executable (checkpoint granularity stays logical — a
    fused sweep resumes and degrades exactly like an unfused one).
    Returns (chi2, vfit, diag, fit_params)."""
    from pint_tpu.runtime import elastic as _elastic

    logical = int(chunk) if chunk else (default_gls_chunk() if gls else 256)
    spans_ = _point_spans(model, parnames, mesh_pts)
    built: dict = {}

    def make_eval(block_size, p):
        # the GLS chunk executable is sized to the rung's block (canary
        # rows included) so fn never re-pads; the WLS path vmaps any
        # batch size through one executable per shape
        fn, free_init, fit_params = build_grid_chi2_fn(
            model, toas, parnames, niter=niter, grid_spans=spans_,
            chunk=block_size if gls else None)
        built["fn"], built["free_init"] = fn, free_init
        built["fit_params"] = fit_params
        sharding = p.batch_sharding()

        if gls:
            def ev(block):
                c2, vf, dg = fn(jnp.asarray(block), sharding=sharding)
                return {"chi2": np.asarray(c2), "vfit": np.asarray(vf),
                        "diag": np.asarray(dg)}
        else:
            def ev(block):
                b = jnp.asarray(block)
                if sharding is not None:
                    b = jax.device_put(b, sharding)
                c2, vf, dg = fn(b)
                return {"chi2": np.asarray(c2), "vfit": np.asarray(vf),
                        "diag": np.asarray(dg)}
        return ev

    make_fused_eval = None
    if gls and fuse is not None and int(fuse) > 1:
        def make_fused_eval(block_size, n_fuse, p):
            fn, free_init, fit_params = build_grid_chi2_fn(
                model, toas, parnames, niter=niter, grid_spans=spans_,
                chunk=block_size)
            built["fn"], built["free_init"] = fn, free_init
            built["fit_params"] = fit_params
            return fn.fused_eval(n_fuse, sharding=p.batch_sharding())

    # prime the fingerprint's free_init without paying a build: it is a
    # pure function of the model's current values and the name order
    all_names = tuple(parnames)
    fit_params0 = tuple(p for p in model.free_params if p not in all_names)
    free_init = _free_init_of(model, fit_params0 + all_names)
    out, report = _elastic.elastic_map(
        make_eval, mesh_pts, plan=plan, chunk=logical,
        checkpoint=checkpoint, retry=retry,
        fingerprint=_grid_fingerprint(tuple(parnames), mesh_pts, niter,
                                      toas, gls, model, free_init),
        what="elastic grid sweep",
        fuse=int(fuse) if fuse else 1,
        make_fused_eval=make_fused_eval)
    ftr.last_elastic_report = report
    if built.get("fn") is not None:
        _attach_grid_executable(ftr, built["fn"], model=model)
    fit_params = built.get("fit_params", fit_params0)
    return out["chi2"], out["vfit"], out["diag"], fit_params


def _point_spans(model, parnames, pts) -> list:
    """Classification spans from an explicit point list: the farthest each
    parameter's points sit from the model's current value.  Shared by every
    grid entry point so identical points always classify — and therefore
    evaluate — identically."""
    spans = []
    for j, p in enumerate(parnames):
        cur = float(getattr(model, p).value or 0.0)
        col = np.asarray(pts)[:, j]
        spans.append(float(np.max(np.abs(col - cur))) if len(col) else 0.0)
    return spans


def grid_chisq_derived(ftr, parnames: Sequence[str], parfuncs: Sequence,
                       gridvalues: Sequence,
                       extraparnames: Sequence[str] = (),
                       niter: int = 4,
                       **kw) -> Tuple[np.ndarray, list, dict]:
    """Grid over derived quantities: each model parameter in ``parnames`` is
    computed as ``parfuncs[i](*gridpoint)`` (reference ``gridutils.py:390``)."""
    model, toas = ftr.model, ftr.toas
    grids = [np.asarray(v, dtype=np.float64) for v in gridvalues]
    shape = tuple(len(g) for g in grids)
    mesh_arrays = np.meshgrid(*grids, indexing="ij")
    flat = [g.ravel() for g in mesh_arrays]
    pts = np.stack(
        [np.asarray([f(*vals) for vals in zip(*flat)], dtype=np.float64)
         for f in parfuncs], axis=-1)
    fn, _, fit_params = build_grid_chi2_fn(
        model, toas, tuple(parnames), niter=niter,
        grid_spans=_point_spans(model, parnames, pts))
    chi2, vfit, diag = fn(jnp.asarray(pts))
    _attach_grid_executable(ftr, fn, model=model)
    _attach_grid_diagnostics(ftr, diag, shape=shape)
    out_grids = [g.reshape(shape) for g in mesh_arrays]
    extraout = _extraout(extraparnames, fit_params, tuple(parnames), vfit,
                         pts, model, shape=shape)
    return np.asarray(chi2).reshape(shape), out_grids, extraout


def tuple_chisq(ftr, parnames: Sequence[str], parvalues: Sequence,
                extraparnames: Sequence[str] = (), niter: int = 4,
                **kw) -> Tuple[np.ndarray, dict]:
    """Chi2 at an explicit list of parameter tuples (reference
    ``gridutils.py:586``)."""
    model, toas = ftr.model, ftr.toas
    pts = np.asarray(parvalues, dtype=np.float64)
    fn, _, fit_params = build_grid_chi2_fn(
        model, toas, tuple(parnames), niter=niter,
        grid_spans=_point_spans(model, parnames, pts))
    chi2, vfit, diag = fn(jnp.asarray(pts))
    _attach_grid_executable(ftr, fn, model=model)
    _attach_grid_diagnostics(ftr, diag)
    extraout = _extraout(extraparnames, fit_params, tuple(parnames), vfit,
                         pts, model)
    return np.asarray(chi2), extraout


def tuple_chisq_derived(ftr, parnames: Sequence[str], parfuncs: Sequence,
                        parvalues: Sequence,
                        extraparnames: Sequence[str] = (), niter: int = 4,
                        **kw) -> Tuple[np.ndarray, list, dict]:
    """Chi2 at explicit tuples of *derived* quantities: model parameter i is
    ``parfuncs[i](*point)`` (reference ``gridutils.py:771``)."""
    model, toas = ftr.model, ftr.toas
    raw = np.asarray(parvalues, dtype=np.float64)
    pts = np.stack(
        [np.asarray([f(*vals) for vals in raw], dtype=np.float64)
         for f in parfuncs], axis=-1)
    fn, _, fit_params = build_grid_chi2_fn(
        model, toas, tuple(parnames), niter=niter,
        grid_spans=_point_spans(model, parnames, pts))
    chi2, vfit, diag = fn(jnp.asarray(pts))
    _attach_grid_executable(ftr, fn, model=model)
    _attach_grid_diagnostics(ftr, diag)
    out_values = [raw[:, i] for i in range(raw.shape[1])]
    extraout = _extraout(extraparnames, fit_params, tuple(parnames), vfit,
                         pts, model)
    return np.asarray(chi2), out_values, extraout
