"""Runtime/example data-path accessors (reference ``config.py``) plus the
device-policy knob consumed by :mod:`pint_tpu.runtime.preflight`."""

from __future__ import annotations

import os

__all__ = ["datadir", "examplefile", "runtimefile",
           "device_policy", "set_device_policy", "DEVICE_POLICIES",
           "ingestion_policy", "set_ingestion_policy", "INGESTION_POLICIES",
           "telemetry_mode", "set_telemetry_mode", "TELEMETRY_MODES",
           "aot_cache_dir", "set_aot_cache_dir",
           "grid_chunk", "set_grid_chunk",
           "tune_dir", "set_tune_dir"]

#: what to do when the preflight probe finds the executing platform differs
#: from the requested one (``PINT_TPU_REQUIRE_PLATFORM``):
#: ``strict`` raises :class:`~pint_tpu.exceptions.DeviceMismatchError`,
#: ``warn`` logs once per process, ``allow`` stays silent (the profile is
#: still attached to results either way).
DEVICE_POLICIES = ("strict", "warn", "allow")

_device_policy = os.environ.get("PINT_TPU_DEVICE_POLICY", "warn")
if _device_policy not in DEVICE_POLICIES:
    _device_policy = "warn"


def device_policy() -> str:
    """Current device-mismatch policy: strict | warn | allow."""
    return _device_policy


def set_device_policy(policy: str) -> None:
    """Set the device-mismatch policy for this process."""
    global _device_policy
    if policy not in DEVICE_POLICIES:
        raise ValueError(
            f"device policy must be one of {DEVICE_POLICIES}, got {policy!r}")
    _device_policy = policy


#: what ingestion (par/tim parsing + TOA validation) does with suspect input
#: (``PINT_TPU_INGESTION_POLICY``): ``strict`` raises a typed
#: :class:`~pint_tpu.exceptions.FileSyntaxError` /
#: :class:`~pint_tpu.exceptions.TOAIntegrityError` on the first problem,
#: ``lenient`` records a :class:`~pint_tpu.integrity.Diagnostics` entry
#: (with a log warning), skips/quarantines the offender, and keeps the good
#: rows, ``collect`` does the same silently so callers can inspect the full
#: report in one pass.
INGESTION_POLICIES = ("strict", "lenient", "collect")

_ingestion_policy = os.environ.get("PINT_TPU_INGESTION_POLICY", "strict")
if _ingestion_policy not in INGESTION_POLICIES:
    _ingestion_policy = "strict"


def ingestion_policy() -> str:
    """Current ingestion policy: strict | lenient | collect."""
    return _ingestion_policy


def set_ingestion_policy(policy: str) -> None:
    """Set the ingestion policy for this process."""
    global _ingestion_policy
    if policy not in INGESTION_POLICIES:
        raise ValueError(
            f"ingestion policy must be one of {INGESTION_POLICIES}, "
            f"got {policy!r}")
    _ingestion_policy = policy


#: how much observability the telemetry subsystem collects
#: (``PINT_TPU_TELEMETRY``): ``off`` keeps every instrumented path on a
#: no-op fast branch (one module-attribute compare, no allocation),
#: ``basic`` records spans/metrics/JAX compile counts in memory, ``full``
#: additionally starts a run manifest + JSONL event stream on disk
#: (:mod:`pint_tpu.telemetry.runlog`) and samples live-buffer watermarks.
TELEMETRY_MODES = ("off", "basic", "full")

_telemetry_mode = os.environ.get("PINT_TPU_TELEMETRY", "off")
if _telemetry_mode not in TELEMETRY_MODES:
    _telemetry_mode = "off"


def telemetry_mode() -> str:
    """Current telemetry mode: off | basic | full."""
    return _telemetry_mode


def set_telemetry_mode(mode: str) -> None:
    """Set the telemetry mode for this process.  Instrumented paths read
    the module attribute directly, so the change is immediate."""
    global _telemetry_mode
    if mode not in TELEMETRY_MODES:
        raise ValueError(
            f"telemetry mode must be one of {TELEMETRY_MODES}, got {mode!r}")
    _telemetry_mode = mode


#: where the warm-serving layer persists AOT artifacts across processes
#: (``PINT_TPU_AOT_CACHE_DIR``): serialized ``jax.export`` executables
#: under ``exports/`` and the XLA persistent compilation cache under
#: ``xla/<device-fingerprint>/`` (:mod:`pint_tpu.serving.aotcache`).
#: ``None`` (the default) disables persistence entirely — the serving
#: layer still works, it just compiles fresh every process.
_aot_cache_dir = os.environ.get("PINT_TPU_AOT_CACHE_DIR") or None


def aot_cache_dir():
    """AOT-cache root directory, or ``None`` when persistence is off.

    The env value is NOT validated at import (a bad env var must not
    break ``import pint_tpu``); :class:`pint_tpu.serving.aotcache.AOTCache`
    raises the typed error on first use, and :func:`set_aot_cache_dir`
    validates eagerly."""
    return _aot_cache_dir


def set_aot_cache_dir(path) -> None:
    """Set (or, with ``None``/empty, disable) the AOT-cache directory
    for this process.  The directory is created if absent; an
    uncreatable or unwritable target raises a typed
    :class:`~pint_tpu.exceptions.UsageError` immediately — a serving
    deployment must learn at configuration time, not at the first cache
    store mid-request."""
    global _aot_cache_dir
    if not path:
        _aot_cache_dir = None
        return
    from pint_tpu.exceptions import UsageError

    path = os.path.abspath(str(path))
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        raise UsageError(
            f"AOT cache dir {path!r} cannot be created: {e}") from e
    if not os.access(path, os.W_OK):
        raise UsageError(
            f"AOT cache dir {path!r} is not writable; executable "
            "persistence needs a writable directory "
            "(PINT_TPU_AOT_CACHE_DIR / set_aot_cache_dir)")
    _aot_cache_dir = path


#: process-wide override of the GLS grid chunk size
#: (``PINT_TPU_GRID_CHUNK`` / :func:`set_grid_chunk`).  ``None`` (the
#: default) lets :func:`pint_tpu.grid.default_gls_chunk` pick the
#: backend's static default — which the autotuner's tuned decisions in
#: turn supersede when ``grid_chisq(chunk="auto")`` finds a manifest.
#: The env value is validated lazily at first :func:`grid_chunk` read
#: (a bad env var must not break ``import pint_tpu``).
_grid_chunk = None
_grid_chunk_env_checked = False


def _coerce_chunk(value, source: str) -> int:
    """Typed validation shared by the setter and the env read: the
    chunk is an executable batch size, so it must be a positive
    integer (a float like 128.5 cannot shape an array axis).  Any
    integral type is accepted (``operator.index`` — numpy integers
    from a parsed sweep row included), matching the grid builder's own
    ``(int, np.integer)`` acceptance."""
    import operator

    from pint_tpu.exceptions import UsageError

    if isinstance(value, bool):
        raise UsageError(
            f"grid chunk from {source} must be a positive integer, "
            f"got {value!r}")
    try:
        chunk = int(value, 10) if isinstance(value, str) \
            else operator.index(value)
    except (TypeError, ValueError):
        raise UsageError(
            f"grid chunk from {source} must be a positive integer, "
            f"got {value!r}") from None
    if chunk <= 0:
        raise UsageError(
            f"grid chunk from {source} must be positive, got {chunk}")
    return chunk


def grid_chunk():
    """The configured GLS grid chunk override, or ``None`` when unset.
    Raises :class:`~pint_tpu.exceptions.UsageError` on a malformed
    ``PINT_TPU_GRID_CHUNK`` value (at read time, not import time)."""
    global _grid_chunk, _grid_chunk_env_checked
    if _grid_chunk is None and not _grid_chunk_env_checked:
        _grid_chunk_env_checked = True
        env = os.environ.get("PINT_TPU_GRID_CHUNK")
        if env:
            _grid_chunk = _coerce_chunk(env, "PINT_TPU_GRID_CHUNK")
    return _grid_chunk


def set_grid_chunk(chunk) -> None:
    """Set (or, with ``None``, clear) the process-wide GLS grid chunk
    override.  Typed :class:`~pint_tpu.exceptions.UsageError` on
    non-positive or non-integer values."""
    global _grid_chunk, _grid_chunk_env_checked
    _grid_chunk_env_checked = True  # an explicit choice wins over env
    if chunk is None:
        _grid_chunk = None
        return
    _grid_chunk = _coerce_chunk(chunk, "set_grid_chunk")


#: where the autotuner persists its tuning manifest across processes
#: (``PINT_TPU_TUNE_DIR`` / :func:`set_tune_dir`): decisions keyed by
#: workload vkey + device fingerprint (:mod:`pint_tpu.autotune`).
#: ``None`` (the default) disables persistence — tunable call sites
#: fall back to the static defaults.
_tune_dir = os.environ.get("PINT_TPU_TUNE_DIR") or None


def tune_dir():
    """Tuning-manifest directory, or ``None`` when autotuning
    persistence is off.  Like :func:`aot_cache_dir`, the env value is
    not validated at import; :class:`pint_tpu.autotune.TuningManifest`
    raises the typed error on first use."""
    return _tune_dir


def set_tune_dir(path) -> None:
    """Set (or, with ``None``/empty, disable) the tuning-manifest
    directory for this process.  Created if absent; an uncreatable or
    unwritable target raises a typed
    :class:`~pint_tpu.exceptions.UsageError` immediately — through the
    ONE validation :class:`pint_tpu.autotune.manifest.TuningManifest`
    itself performs, so the eager check here and the lazy first-use
    check cannot drift apart."""
    global _tune_dir
    if not path:
        _tune_dir = None
        return
    path = os.path.abspath(str(path))
    from pint_tpu.autotune.manifest import TuningManifest

    TuningManifest(path)  # typed UsageError on uncreatable/unwritable
    _tune_dir = path


def datadir() -> str:
    """Directory holding packaged data files."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def examplefile(filename: str) -> str:
    """Full path of a packaged example file (reference ``config.py:34``)."""
    path = os.path.join(datadir(), "examples", filename)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path


def runtimefile(filename: str) -> str:
    """Full path of a packaged runtime file (reference ``config.py:46``)."""
    path = os.path.join(datadir(), "runtime", filename)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path
