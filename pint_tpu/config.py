"""Runtime/example data-path accessors (reference ``config.py``)."""

from __future__ import annotations

import os

__all__ = ["datadir", "examplefile", "runtimefile"]


def datadir() -> str:
    """Directory holding packaged data files."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def examplefile(filename: str) -> str:
    """Full path of a packaged example file (reference ``config.py:34``)."""
    path = os.path.join(datadir(), "examples", filename)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path


def runtimefile(filename: str) -> str:
    """Full path of a packaged runtime file (reference ``config.py:46``)."""
    path = os.path.join(datadir(), "runtime", filename)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path
