"""Runtime/example data-path accessors (reference ``config.py``) plus the
device-policy knob consumed by :mod:`pint_tpu.runtime.preflight`."""

from __future__ import annotations

import os

__all__ = ["datadir", "examplefile", "runtimefile",
           "device_policy", "set_device_policy", "DEVICE_POLICIES",
           "ingestion_policy", "set_ingestion_policy", "INGESTION_POLICIES",
           "telemetry_mode", "set_telemetry_mode", "TELEMETRY_MODES",
           "aot_cache_dir", "set_aot_cache_dir"]

#: what to do when the preflight probe finds the executing platform differs
#: from the requested one (``PINT_TPU_REQUIRE_PLATFORM``):
#: ``strict`` raises :class:`~pint_tpu.exceptions.DeviceMismatchError`,
#: ``warn`` logs once per process, ``allow`` stays silent (the profile is
#: still attached to results either way).
DEVICE_POLICIES = ("strict", "warn", "allow")

_device_policy = os.environ.get("PINT_TPU_DEVICE_POLICY", "warn")
if _device_policy not in DEVICE_POLICIES:
    _device_policy = "warn"


def device_policy() -> str:
    """Current device-mismatch policy: strict | warn | allow."""
    return _device_policy


def set_device_policy(policy: str) -> None:
    """Set the device-mismatch policy for this process."""
    global _device_policy
    if policy not in DEVICE_POLICIES:
        raise ValueError(
            f"device policy must be one of {DEVICE_POLICIES}, got {policy!r}")
    _device_policy = policy


#: what ingestion (par/tim parsing + TOA validation) does with suspect input
#: (``PINT_TPU_INGESTION_POLICY``): ``strict`` raises a typed
#: :class:`~pint_tpu.exceptions.FileSyntaxError` /
#: :class:`~pint_tpu.exceptions.TOAIntegrityError` on the first problem,
#: ``lenient`` records a :class:`~pint_tpu.integrity.Diagnostics` entry
#: (with a log warning), skips/quarantines the offender, and keeps the good
#: rows, ``collect`` does the same silently so callers can inspect the full
#: report in one pass.
INGESTION_POLICIES = ("strict", "lenient", "collect")

_ingestion_policy = os.environ.get("PINT_TPU_INGESTION_POLICY", "strict")
if _ingestion_policy not in INGESTION_POLICIES:
    _ingestion_policy = "strict"


def ingestion_policy() -> str:
    """Current ingestion policy: strict | lenient | collect."""
    return _ingestion_policy


def set_ingestion_policy(policy: str) -> None:
    """Set the ingestion policy for this process."""
    global _ingestion_policy
    if policy not in INGESTION_POLICIES:
        raise ValueError(
            f"ingestion policy must be one of {INGESTION_POLICIES}, "
            f"got {policy!r}")
    _ingestion_policy = policy


#: how much observability the telemetry subsystem collects
#: (``PINT_TPU_TELEMETRY``): ``off`` keeps every instrumented path on a
#: no-op fast branch (one module-attribute compare, no allocation),
#: ``basic`` records spans/metrics/JAX compile counts in memory, ``full``
#: additionally starts a run manifest + JSONL event stream on disk
#: (:mod:`pint_tpu.telemetry.runlog`) and samples live-buffer watermarks.
TELEMETRY_MODES = ("off", "basic", "full")

_telemetry_mode = os.environ.get("PINT_TPU_TELEMETRY", "off")
if _telemetry_mode not in TELEMETRY_MODES:
    _telemetry_mode = "off"


def telemetry_mode() -> str:
    """Current telemetry mode: off | basic | full."""
    return _telemetry_mode


def set_telemetry_mode(mode: str) -> None:
    """Set the telemetry mode for this process.  Instrumented paths read
    the module attribute directly, so the change is immediate."""
    global _telemetry_mode
    if mode not in TELEMETRY_MODES:
        raise ValueError(
            f"telemetry mode must be one of {TELEMETRY_MODES}, got {mode!r}")
    _telemetry_mode = mode


#: where the warm-serving layer persists AOT artifacts across processes
#: (``PINT_TPU_AOT_CACHE_DIR``): serialized ``jax.export`` executables
#: under ``exports/`` and the XLA persistent compilation cache under
#: ``xla/<device-fingerprint>/`` (:mod:`pint_tpu.serving.aotcache`).
#: ``None`` (the default) disables persistence entirely — the serving
#: layer still works, it just compiles fresh every process.
_aot_cache_dir = os.environ.get("PINT_TPU_AOT_CACHE_DIR") or None


def aot_cache_dir():
    """AOT-cache root directory, or ``None`` when persistence is off.

    The env value is NOT validated at import (a bad env var must not
    break ``import pint_tpu``); :class:`pint_tpu.serving.aotcache.AOTCache`
    raises the typed error on first use, and :func:`set_aot_cache_dir`
    validates eagerly."""
    return _aot_cache_dir


def set_aot_cache_dir(path) -> None:
    """Set (or, with ``None``/empty, disable) the AOT-cache directory
    for this process.  The directory is created if absent; an
    uncreatable or unwritable target raises a typed
    :class:`~pint_tpu.exceptions.UsageError` immediately — a serving
    deployment must learn at configuration time, not at the first cache
    store mid-request."""
    global _aot_cache_dir
    if not path:
        _aot_cache_dir = None
        return
    from pint_tpu.exceptions import UsageError

    path = os.path.abspath(str(path))
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        raise UsageError(
            f"AOT cache dir {path!r} cannot be created: {e}") from e
    if not os.access(path, os.W_OK):
        raise UsageError(
            f"AOT cache dir {path!r} is not writable; executable "
            "persistence needs a writable directory "
            "(PINT_TPU_AOT_CACHE_DIR / set_aot_cache_dir)")
    _aot_cache_dir = path


def datadir() -> str:
    """Directory holding packaged data files."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def examplefile(filename: str) -> str:
    """Full path of a packaged example file (reference ``config.py:34``)."""
    path = os.path.join(datadir(), "examples", filename)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path


def runtimefile(filename: str) -> str:
    """Full path of a packaged runtime file (reference ``config.py:46``)."""
    path = os.path.join(datadir(), "runtime", filename)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path
