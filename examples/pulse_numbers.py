"""Phase connection and pulse-number tracking.

The TPU-native analogue of the reference's
``docs/examples/example_pulse_numbers.py`` and ``check_phase_connection.py``:
residuals track either the nearest pulse (``track_mode="nearest"``) or
recorded pulse numbers (``track_mode="use_pulse_numbers"``); the latter is
what keeps a fit honest when a trial model walks residuals across a phase
wrap, and ``delta_pulse_number`` lets you add deliberate phase wraps.

Run:  python examples/pulse_numbers.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(PAR)
    toas = make_fake_toas_uniform(53500, 54200, 60, model, error_us=30.0,
                                  add_noise=True,
                                  rng=np.random.default_rng(11))

    # stamp the model's pulse numbering onto the TOAs
    pn = toas.compute_pulse_numbers(model)
    print(f"pulse numbers span {int(pn.min())} .. {int(pn.max())} "
          f"({int(pn.max() - pn.min())} rotations over the data)")

    # --- a model error larger than half a pulse ---------------------------
    # F0 off by ~1.5 turns over the span: nearest-pulse tracking silently
    # wraps; pulse-number tracking shows the real, growing offset.
    bad = get_model(PAR)
    span_s = (54200 - 53500) * 86400.0
    bad.F0.value += 1.5 / span_s

    r_near = Residuals(toas, bad, track_mode="nearest")
    r_track = Residuals(toas, bad, track_mode="use_pulse_numbers")
    p = float(1.0 / model.F0.value)
    ptp_near = float(np.ptp(np.asarray(r_near.time_resids)))
    ptp_track = float(np.ptp(np.asarray(r_track.time_resids)))
    print(f"nearest-pulse residual swing: {ptp_near * 1e3:8.3f} ms "
          f"(wrapped into one period, {p * 1e3:.3f} ms)")
    print(f"tracked       residual swing: {ptp_track * 1e3:8.3f} ms "
          f"(the full {1.5:.1f}-turn drift)")
    assert ptp_near < 1.05 * p
    assert ptp_track > 1.3 * p

    # a tracked fit recovers the truth even across the wrap
    f = WLSFitter(toas, bad, track_mode="use_pulse_numbers")
    f.fit_toas()
    pull = (f.model.F0.value - model.F0.value) / f.model.F0.uncertainty_value
    print(f"tracked fit recovers F0 to {pull:+5.2f} sigma")
    assert abs(pull) < 4

    # --- deliberate phase wraps -------------------------------------------
    toas.delta_pulse_number = np.zeros(len(toas))
    toas.delta_pulse_number[30:] = +1  # one extra rotation after a gap
    r_wrap = Residuals(toas, model, track_mode="use_pulse_numbers")
    step = (np.asarray(r_wrap.time_resids)[30:].mean()
            - np.asarray(r_wrap.time_resids)[:30].mean())
    print(f"delta_pulse_number wrap shifts the second half by "
          f"{step * 1e3:+.3f} ms (one period = {p * 1e3:.3f} ms)")
    assert abs(step - p) < 0.1 * p
    return 0


if __name__ == "__main__":
    sys.exit(main())
