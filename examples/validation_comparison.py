"""Tempo2-style model validation: compare two fitted models parameter by
parameter with sigma-change columns.

The TPU-native analogue of the reference's "comparing models / checking
your fit" workflow (``timing_model.compare``, reference
``timing_model.py:2293``): fit NGC6440E, compare the post-fit model to the
par-file model at every verbosity level, and flag parameters that moved by
more than a chosen threshold — the same table a tempo2 user reads off
``compare`` output.

Run:  python examples/validation_comparison.py [--cpu]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model_and_toas

    model, toas = get_model_and_toas(PAR, TIM)
    initial = model  # keep the par-file values
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=4)
    fitted = f.model

    # full table: every parameter, values +/- uncertainties, sigma shifts
    table = fitted.compare(initial, verbosity="max")
    print(table)
    assert "Diff_Sigma1" in table and "F0" in table

    # "check" verbosity: just the names that moved beyond the threshold —
    # the quick validation sweep one runs after any refit
    moved = fitted.compare(initial, verbosity="check", threshold_sigma=3.0)
    print(f"parameters moved > 3 sigma: {moved.split() or '(none)'}")

    # a deliberately perturbed model must get flagged
    import copy

    wrong = copy.deepcopy(fitted)
    wrong.F0.value = wrong.F0.value + 50 * float(wrong.F0.uncertainty or 1e-9)
    flagged = fitted.compare(wrong, verbosity="check")
    assert "F0" in flagged
    print("perturbed-F0 model correctly flagged by compare(check)")
    print("validation comparison done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
