"""Choosing a fitter: what Fitter.auto picks and why.

The reference's fitter-selection guidance (``fitter.py:193 Fitter.auto``,
"which fitter should I use?"): WLS for uncorrelated white noise, GLS once
the model has correlated noise (ECORR/red noise), wideband fitters when
the TOAs carry DM measurements — each in plain and Downhill (step-halving)
variants.  This walkthrough builds all three data/model situations and
shows the dispatch, then demonstrates why Downhill matters on a start
point a plain WLS step overshoots.

Run:  python examples/fitter_selection.py [--cpu]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """\
PSR PICKME
RAJ 12:00:00
DECJ 30:00:00
POSEPOCH 55500
F0 50.0 1
F1 -1e-15 1
PEPOCH 55500
DM 15.0
UNITS TDB
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import Fitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    rng = np.random.default_rng(3)
    white = get_model(io.StringIO(PAR))
    toas = make_fake_toas_uniform(55000, 56000, 50, white, error_us=10.0,
                                  add_noise=True, rng=rng)

    # 1. uncorrelated white noise -> (Downhill)WLS
    f1 = Fitter.auto(toas, white)
    print(f"white-noise model        -> {type(f1).__name__}")
    assert "WLS" in type(f1).__name__

    # 2. correlated noise in the model -> (Downhill)GLS
    corr = get_model(io.StringIO(
        PAR + "ECORR mjd 50000 60000 1.5\nTNREDAMP -13.5\nTNREDGAM 3.0\n"
              "TNREDC 10\n"))
    f2 = Fitter.auto(toas, corr)
    print(f"ECORR + red-noise model  -> {type(f2).__name__}")
    assert "GLS" in type(f2).__name__

    # 3. wideband TOAs (per-TOA DM measurements) -> wideband fitter
    wb_toas = make_fake_toas_uniform(55000, 56000, 50, white, error_us=10.0,
                                     add_noise=True, wideband=True, rng=rng)
    f3 = Fitter.auto(wb_toas, get_model(io.StringIO(PAR)))
    print(f"wideband TOAs            -> {type(f3).__name__}")
    assert "Wideband" in type(f3).__name__

    # plain (non-downhill) dispatch is one flag away
    f4 = Fitter.auto(toas, corr, downhill=False)
    print(f"downhill=False           -> {type(f4).__name__}")
    assert type(f4).__name__ == "GLSFitter"

    # 4. why Downhill: from a start point where one full GN step overshoots
    # (F0 off by ~half the aliasing scale), step-halving still converges
    far = get_model(io.StringIO(PAR))
    far.F0.value = far.F0.value + 4e-9
    chi2 = Fitter.auto(toas, far).fit_toas(maxiter=8)
    dof = len(toas) - len(far.free_params) - 1
    print(f"downhill WLS from a far start: chi2/dof = {chi2 / dof:.2f}")
    assert chi2 / dof < 2.0

    for f in (f1, f2, f3):
        c = f.fit_toas(maxiter=2)
        assert np.isfinite(c)
    print("all selected fitters converge on their data")
    print("fitter selection done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
