"""Phase connection: pulse numbering, tracking modes, and spotting a
broken solution.

The reference workflow ("check_phase_connection" /
``docs/examples/How_to_track_phase``): compute absolute pulse numbers at a
good solution, show that nearest-integer tracking and pulse-number
tracking agree there, then degrade F0 until the solution wraps — the
pulse-number track keeps the (now huge, smooth) residuals while nearest
tracking aliases them back into +-0.5 cycles, and chi2 exposes the break.

Run:  python examples/phase_connection.py [--cpu]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """\
PSR CONNECT
RAJ 6:30:00
DECJ -10:00:00
POSEPOCH 55500
F0 311.49339 1
F1 -1.1e-15 1
PEPOCH 55500
DM 40.0
TZRMJD 55500
TZRFRQ 1400
TZRSITE gbt
UNITS TDB
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(io.StringIO(PAR))
    toas = make_fake_toas_uniform(55300, 55700, 60, model, error_us=15.0,
                                  obs="gbt", add_noise=True,
                                  rng=np.random.default_rng(42))

    # 1. at the true solution: assign absolute pulse numbers
    toas.compute_pulse_numbers(model)
    pn = np.asarray(toas.pulse_number)
    assert np.all(pn == np.round(pn))
    print(f"pulse numbers span {pn.min():.0f} .. {pn.max():.0f} "
          f"({len(np.unique(pn))} distinct pulses)")

    r_near = Residuals(toas, model, track_mode="nearest")
    r_pn = Residuals(toas, model, track_mode="use_pulse_numbers")
    agree = np.allclose(np.asarray(r_near.time_resids),
                        np.asarray(r_pn.time_resids), atol=1e-12)
    print(f"connected solution: nearest == pulse-number tracking: {agree}")
    assert agree

    # 2. break the connection: shift F0 by ~2 turns over the half-span
    import copy

    broken = copy.deepcopy(model)
    span_s = 200 * 86400.0
    broken.F0.value = broken.F0.value + 2.0 / span_s
    rb_near = Residuals(toas, broken, track_mode="nearest")
    rb_pn = Residuals(toas, broken, track_mode="use_pulse_numbers")
    # nearest tracking aliases into +-0.5 cycles; pulse numbers do not
    assert np.max(np.abs(np.asarray(rb_near.phase_resids))) <= 0.5
    assert np.max(np.abs(np.asarray(rb_pn.phase_resids))) > 1.0
    print(f"broken solution: nearest-track max |phase| = "
          f"{np.max(np.abs(np.asarray(rb_near.phase_resids))):.2f} cyc "
          f"(aliased), pulse-number max |phase| = "
          f"{np.max(np.abs(np.asarray(rb_pn.phase_resids))):.1f} cyc (true)")

    # 3. chi2 ratio is the phase-connection alarm either way
    ratio = rb_near.chi2 / r_near.chi2
    print(f"chi2 blow-up factor on the broken model: {ratio:.1f}x")
    assert ratio > 50
    print("phase connection check done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
