"""DDK / Kopeikin binary analysis: annual-parallax orbital corrections.

The reference's DDK workflow (``binary_ddk.py``, e.g. J0437-4715-style
analyses): the DDK model corrects the DD orbit for the annual motion of
the Earth across a nearby pulsar's orbit (Kopeikin 1995) and for secular
proper-motion terms (Kopeikin 1996), turning PX/KIN/KOM into measurable
quantities.  This walkthrough shows the Kopeikin delay signature (DDK vs
plain DD) and then fits orbital parameters on simulated DDK data.

Run:  python examples/ddk_kopeikin_fit.py [--quick] [--cpu]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = """\
PSR KOPEIKIN
RAJ 4:37:15.8
DECJ -47:15:08.6
PMRA 121.4
PMDEC -71.5
PX 6.4
POSEPOCH 55500
F0 173.6879 1
F1 -1.7e-15 1
PEPOCH 55500
DM 2.64
UNITS TDB
"""
ORBIT = "PB 5.741 1\nA1 3.3667 1\nECC 1.9e-5\nOM 1.0\nT0 55492.0\nM2 0.224\n"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    ddk = get_model(io.StringIO(
        BASE + "BINARY DDK\n" + ORBIT + "KIN 137.6\nKOM 207.0\nK96 1\n"))
    dd = get_model(io.StringIO(
        BASE + "BINARY DD\n" + ORBIT + "SINI 0.674\n"))

    n = 80 if quick else 200
    rng = np.random.default_rng(21)
    toas = make_fake_toas_uniform(55000, 56000, n, ddk, error_us=1.0,
                                  add_noise=True, rng=rng)

    # 1. the Kopeikin signature: DDK minus DD binary delay, annual + secular
    d_ddk = np.asarray(ddk.delay(toas))
    d_dd = np.asarray(dd.delay(toas))
    sig_us = 1e6 * (d_ddk - d_dd)
    sig_us -= sig_us.mean()
    print(f"Kopeikin correction signature: peak-to-peak "
          f"{sig_us.max() - sig_us.min():.2f} us over 1000 d "
          f"(annual orbital parallax + PM secular terms)")
    assert sig_us.max() - sig_us.min() > 0.5  # resolvable at 1 us TOAs

    # 2. fit the orbit on the DDK data starting slightly off
    import copy

    start = copy.deepcopy(ddk)
    start.A1.value = start.A1.value + 3e-6
    start.PB.value = start.PB.value + 2e-8
    f = WLSFitter(toas, start)
    f.fit_toas(maxiter=4)
    a1 = float(f.model.A1.value)
    pb = float(f.model.PB.value)
    print(f"fitted A1 = {a1:.8f} ls (true 3.3667), "
          f"PB = {pb:.9f} d (true 5.741)")
    assert abs(a1 - 3.3667) < 5e-6
    assert abs(pb - 5.741) < 5e-7
    chi2r = f.resids.chi2 / f.resids.dof
    print(f"post-fit reduced chi2 = {chi2r:.2f}")
    assert chi2r < 2.0
    print("DDK Kopeikin fit done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
