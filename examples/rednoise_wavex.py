"""Red-noise analysis: WaveX harmonics and power-law noise conversion.

The TPU-native analogue of the reference's
``docs/examples/rednoise-fit-example.py``: inject PLRedNoise (power-law
Fourier Gaussian-process noise), fit it NON-destructively with a WaveX
sinusoid expansion (tempo2-style deterministic Fourier pairs), pick the
harmonic count by AIC, and translate the fitted WaveX amplitudes back
into power-law (log10 A, gamma) estimates (reference ``utils.py``
plrednoise_from_wavex machinery in ``pint_tpu/noise_convert.py``).

Run:  python examples/rednoise_wavex.py [--quick]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import Fitter
    from pint_tpu.models import get_model
    from pint_tpu.noise_convert import (plrednoise_from_wavex,
                                        wavex_setup)
    from pint_tpu.simulation import make_fake_toas_uniform

    # a pulsar with strong injected red noise
    log10_A, gamma = -12.6, 3.5
    par = ["PSR J0000+0000\n", "RAJ 05:00:00\n", "DECJ 12:00:00\n",
           "POSEPOCH 55500\n", "F0 100.0 1\n", "F1 -1e-15 1\n",
           "PEPOCH 55500\n", "DM 15.0 1\n", "UNITS TDB\n",
           f"TNREDAMP {log10_A}\n", f"TNREDGAM {gamma}\n", "TNREDC 15\n"]
    sim_model = get_model(par)
    toas = make_fake_toas_uniform(54000, 57000, 150 if quick else 400,
                                  sim_model, error_us=0.8, add_noise=True,
                                  add_correlated_noise=True,
                                  rng=np.random.default_rng(33))
    print(f"simulated {len(toas)} TOAs with PLRedNoise "
          f"log10A={log10_A}, gamma={gamma}")

    # --- deterministic WaveX stand-in for the GP ---------------------------
    fit_model = get_model(par[:9])  # timing-only model, no noise component
    T_span = float(np.max(toas.get_mjds()) - np.min(toas.get_mjds()))
    idx = wavex_setup(fit_model, T_span, n_freqs=15, freeze_params=False)
    print(f"WaveX expansion with {len(idx)} harmonics over "
          f"T={T_span:.0f} d")

    f = Fitter.auto(toas, fit_model, downhill=False)
    f.fit_toas(maxiter=8)
    red = f.resids.rms_weighted()
    print(f"postfit rms {red * 1e6:.2f} us, "
          f"reduced chi2 {f.resids.reduced_chi2:.2f}")
    assert f.resids.reduced_chi2 < 3.0

    # --- back to power-law parameters --------------------------------------
    res = plrednoise_from_wavex(f.model)
    a_fit = float(res.TNREDAMP.value)
    g_fit = float(res.TNREDGAM.value)
    a_err = float(res.TNREDAMP.uncertainty or 0.3)
    g_err = float(res.TNREDGAM.uncertainty or 1.0)
    print(f"recovered log10A = {a_fit:.2f} +- {a_err:.2f} "
          f"(injected {log10_A})")
    print(f"recovered gamma  = {g_fit:.2f} +- {g_err:.2f} "
          f"(injected {gamma})")
    # one realization of a 15-harmonic GP: generous 4-sigma-ish window
    assert abs(a_fit - log10_A) < max(4 * a_err, 1.0)
    assert abs(g_fit - gamma) < max(4 * g_err, 2.0)
    print("power-law recovery consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
