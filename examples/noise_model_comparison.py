"""Noise-model selection with AIC/BIC: does this dataset need EFAC/EQUAD?

The reference's noise-model comparison workflow ("compare noise models",
``utils.akaike_information_criterion`` / ``bayesian_information_criterion``):
simulate TOAs whose real scatter is errors scaled by 1.4 plus a 2 us floor,
ML-fit the noise parameters (alternating timing/noise rounds, reference
``fitter.py:1086``), and let the information criteria pick the white-noise
model over the bare one — then verify they do NOT over-select on clean data.

Run:  python examples/noise_model_comparison.py [--quick] [--cpu]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """\
PSR NOISY
RAJ 9:00:00
DECJ 5:00:00
POSEPOCH 55500
F0 215.0 1
F1 -9e-16 1
PEPOCH 55500
DM 25.0
UNITS TDB
"""
NOISE = "EFAC mjd 50000 60000 1.4\nEQUAD mjd 50000 60000 2.0\n"


def _fit_and_ll(partext, toas, fit_noise):
    from pint_tpu.fitter import DownhillWLSFitter, WLSFitter
    from pint_tpu.models import get_model

    m = get_model(io.StringIO(partext))
    if fit_noise:
        # unfreeze the white-noise parameters: DownhillFitter.fit_toas then
        # alternates (timing fit, ML noise fit) rounds automatically
        m.EFAC1.frozen = False
        m.EQUAD1.frozen = False
        f = DownhillWLSFitter(toas, m)
        f.fit_toas(maxiter=6, noise_fit_niter=2)
    else:
        f = WLSFitter(toas, m)
        f.fit_toas(maxiter=3)
    return f, f.resids.lnlikelihood()


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.utils import (akaike_information_criterion,
                                bayesian_information_criterion)

    n = 80 if quick else 200
    rng = np.random.default_rng(7)
    truth = get_model(io.StringIO(PAR + NOISE))  # EFAC 1.4, EQUAD 2 us
    # VARIED TOA errors: with one common error value EFAC (multiplicative)
    # and EQUAD (additive floor) are exactly degenerate and unfittable
    errs = rng.uniform(1.5, 6.0, n)
    toas = make_fake_toas_uniform(55000, 56000, n, truth, error_us=errs,
                                  add_noise=True, rng=rng)

    f_bare, ll_bare = _fit_and_ll(PAR, toas, fit_noise=False)
    f_noise, ll_noise = _fit_and_ll(
        PAR + "EFAC mjd 50000 60000 1.0\nEQUAD mjd 50000 60000 0.5\n",
        toas, fit_noise=True)
    efac = float(f_noise.model.EFAC1.value)
    equad = float(f_noise.model.EQUAD1.value)
    print(f"ML noise fit: EFAC = {efac:.2f} (true 1.4), "
          f"EQUAD = {equad:.2f} us (true 2.0)")
    assert 1.0 < efac < 1.9 and 0.8 < equad < 3.5

    k_bare = len(f_bare.model.free_params)
    k_noise = len(f_noise.model.free_params)  # EFAC1/EQUAD1 included (free)
    assert k_noise == k_bare + 2
    aic_bare = akaike_information_criterion(ll_bare, k_bare)
    aic_noise = akaike_information_criterion(ll_noise, k_noise)
    bic_bare = bayesian_information_criterion(ll_bare, k_bare, n)
    bic_noise = bayesian_information_criterion(ll_noise, k_noise, n)
    print(f"AIC: bare {aic_bare:.1f} vs noise {aic_noise:.1f} "
          f"(delta {aic_bare - aic_noise:+.1f})")
    print(f"BIC: bare {bic_bare:.1f} vs noise {bic_noise:.1f} "
          f"(delta {bic_bare - bic_noise:+.1f})")
    assert aic_noise < aic_bare and bic_noise < bic_bare
    print("information criteria select the EFAC/EQUAD model on noisy data")

    # control: clean data must NOT prefer the extra parameters strongly
    rng2 = np.random.default_rng(8)
    toas_clean = make_fake_toas_uniform(55000, 56000, n,
                                        get_model(io.StringIO(PAR)),
                                        error_us=rng2.uniform(1.5, 6.0, n),
                                        add_noise=True, rng=rng2)
    _, ll_b2 = _fit_and_ll(PAR, toas_clean, fit_noise=False)
    _, ll_n2 = _fit_and_ll(
        PAR + "EFAC mjd 50000 60000 1.0\nEQUAD mjd 50000 60000 0.5\n",
        toas_clean, fit_noise=True)
    d_bic = bayesian_information_criterion(ll_b2, k_bare, n) \
        - bayesian_information_criterion(ll_n2, k_noise, n)
    print(f"clean-data BIC delta (bare - noise) = {d_bic:+.1f} "
          "(<~ the 2-parameter penalty: no over-selection)")
    assert d_bic < 6.0
    print("noise-model comparison done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
