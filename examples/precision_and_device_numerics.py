"""Precision and device numerics: how timing stays exact on emulated f64.

Pulsar timing needs ~1e-15 relative precision on pulse phase (nanoseconds
over decades).  The reference framework gets it from numpy's 80-bit
``np.longdouble``; there is no longdouble on an accelerator, so this
framework carries time as **double-double pairs** (``pint_tpu.dd``) and
pulse phase as an explicit (integer, fractional) pair
(``pint_tpu.phase.Phase``).  This walkthrough demonstrates the numerical
model a user should have in mind, on whatever backend it runs:

1. why a single f64 cannot hold an MJD epoch to timing precision,
2. dd arithmetic recovering the lost bits,
3. the exact-by-construction phase fold (``mul_mod1``) that stays correct
   even on TPUs, where f64 is *emulated* with float32-range arithmetic
   and classic double-double silently degrades (DESIGN.md),
4. the float32-RANGE rule for on-device graphs: why the correlated-noise
   likelihood uses the scaled-basis Woodbury form (no ``1/phi``, no
   ``log phi``) and a 1e10 offset prior instead of enterprise's 1e40,
5. the measured device bounds a TPU user can rely on (and how to
   re-assert them with ``tools/tpu_precision_check.py``).

Run:  python examples/precision_and_device_numerics.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args or True:  # CPU is the precision reference; always pin
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    # -- 1. the f64 problem -------------------------------------------------
    # An MJD like 53750.000001 carries ~5e10 seconds since MJD 0; one f64
    # ulp at that scale is ~7.6e-6 s — four orders of magnitude too coarse
    # for 1-ns timing.
    mjd = 53750.000001
    t_sec = mjd * 86400.0
    ulp = np.spacing(t_sec)
    print(f"epoch as one f64: {t_sec:.6f} s, ulp = {ulp:.2e} s "
          f"(need ~1e-9 s)")
    assert ulp > 1e-7

    # -- 2. dd pairs recover the bits ---------------------------------------
    from pint_tpu.dd import dd_from_longdouble, day2sec_exact

    mjd_ld = np.longdouble("53750.000001")
    hi, lo = dd_from_longdouble(mjd_ld * np.longdouble(86400.0))
    err_vs_ld = float(abs((np.longdouble(hi) + np.longdouble(lo))
                          - mjd_ld * np.longdouble(86400.0)))
    print(f"dd pair: hi={hi!r}, lo={lo!r}; |dd - longdouble| = "
          f"{err_vs_ld:.2e} s")
    assert err_vs_ld < 1e-9
    # day->second conversion as an unevaluated 2-term sum: no bits are
    # rounded away (dd.day2sec_exact)
    e1, e2 = day2sec_exact(jnp.asarray([53750.000001]))
    print(f"day2sec_exact: e1={float(e1[0])!r} e2={float(e2[0])!r}")

    # -- 3. the exact phase fold --------------------------------------------
    # phase = F0 * t mod 1 is THE precision-critical product: F0 ~ 1e2 Hz,
    # t ~ 1e9 s -> phase ~ 1e11 cycles, of which only the fractional part
    # matters.  mul_mod1 folds each exact time component against F0
    # separately with power-of-two splits whose dominant partial products
    # are exactly representable, so the result does not depend on IEEE
    # rounding semantics — the property that survives TPU's
    # excess-precision emulated f64, where textbook two_sum compensation
    # collapses (DESIGN.md, measured).  Only phases are combined (integer
    # parts exact, fractions small).
    from pint_tpu.dd import mul_mod1

    F0 = 61.4854765456
    k1, f1 = mul_mod1(F0, e1)
    k2, f2 = mul_mod1(F0, e2)
    f = float(f1[0] + f2[0])
    f -= round(f)
    # 40-digit reference via mpmath
    import mpmath as mp

    with mp.workdps(40):
        ph = (mp.mpf(float(e1[0])) + mp.mpf(float(e2[0]))) * mp.mpf(F0)
        frac_ref = float(ph - mp.nint(ph))
    err_cycles = abs(f - frac_ref)
    err_cycles = min(err_cycles, abs(1.0 - err_cycles))  # wrap distance
    print(f"mul_mod1 fractional phase vs 40-digit mpmath: "
          f"|d| = {err_cycles:.2e} cycles")
    # documented fold bound ~2^-31 cycles (dd.py); TPU storage floor ~5e-5
    assert err_cycles < 1e-8

    # -- 4. the float32-RANGE rule for device graphs ------------------------
    # TPU emulates f64 with float32-range arithmetic: values outside
    # ~[1e-38, 3e38] flush or overflow INSIDE jitted graphs even though
    # the same f64 computation is fine on CPU.  The correlated-noise
    # likelihood is the canonical trap: the marginalized-offset prior is
    # conventionally 1e40, and both log(phi) and sqrt(phi)-scaled basis
    # columns blow past f32 range.  The framework's woodbury_dot therefore
    # uses Sigma = I + V^T N^-1 V with V = U sqrt(phi) and the determinant
    # lemma for logdet — no 1/phi, no log(phi) — and the offset prior is
    # OFFSET_PRIOR_WEIGHT = 1e10 s^2 (uninformative by ~26 orders).
    from pint_tpu.models.timing_model import OFFSET_PRIOR_WEIGHT
    from pint_tpu.utils import woodbury_dot

    rng = np.random.default_rng(0)
    n, m = 50, 5
    U = np.hstack([rng.standard_normal((n, m - 1)), np.ones((n, 1))])
    sigma2 = rng.uniform(0.5, 2.0, n) * 1e-12
    r = rng.standard_normal(n) * 1e-6
    phi = np.array([1e-18, 1e-16, 1e-14, 1e-12, OFFSET_PRIOR_WEIGHT])
    dot, logdet = jax.jit(woodbury_dot)(
        jnp.asarray(sigma2), jnp.asarray(U), jnp.asarray(phi),
        jnp.asarray(r), jnp.asarray(r))
    print(f"woodbury chi2 = {float(dot):.3f}, logdet = {float(logdet):.3f} "
          f"(offset prior {OFFSET_PRIOR_WEIGHT:.0e}, finite by design)")
    assert np.isfinite(float(dot)) and np.isfinite(float(logdet))

    # -- 5. what a TPU user can rely on -------------------------------------
    print("""
measured device bounds (v5e, re-assertable with
  PINT_TPU_TESTS=1 pytest tests/test_tpu_precision.py
or tools/tpu_precision_check.py --auto on a live TPU):
  pulse integers          identical to CPU
  fractional phase        <= 1e-4 cycles   (measured ~5e-5)
  delay components        <= 1e-9 s
  Woodbury chi2+logdet    <= 1e-9 relative on identical inputs
                          (measured 7.7e-14; dots/reductions ~1e-14)
  chi2-level quantities   deviate only by the phase floor propagated
                          through 1/sigma^2 (explained-deviation bounds)
""")
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
