"""Performance benchmarking: measure fitting throughput the right way.

The TPU-native analogue of the reference's ``profiling/`` workflow
(``profiling/bench_chisq_grid.py``, ``bench_MCMC.py``,
``high_level_benchmark.py``): time a chi2 grid and an MCMC fit, with the
three rules that make the numbers meaningful on a jit/XLA stack:

1. **Warm before you time.**  The first call traces + compiles (seconds
   to minutes on a remote TPU); repeats replay from cache.  Warm with a
   2-corner-point grid spanning the FULL grid range so the compiled
   executable, the linear-column classification, and the hoisted
   per-grid constants are all reused verbatim inside the timed region.
2. **Match the chunk to the workload (or keep the default).**  GLS grid
   points run through a fixed-size chunked executable
   (``grid.default_gls_chunk`` = 128, from the round-5 on-TPU sweep).
   A grid that is exactly one chunk (e.g. ``chunk=256`` for a 16x16
   grid, as bench.py pins) avoids per-chunk dispatch; the chunk must be
   the SAME in the warm and timed calls — it keys the executable.
3. **Sanity-check the physics, not just the clock.**  A throughput
   number only counts if the grid minimum equals the fitter's chi2 at
   the same argmin (the bench's ``sanity_ok`` contract).

The repo-root ``bench.py`` is the production version of this flow
(B1855+09, 4005 TOAs, 90 free parameters; measurement history in
BENCH_NOTES.md).  This walkthrough runs the same shape at CI size.

Run:  python examples/performance_benchmarking.py [--cpu] [--quick]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")
    quick = "--quick" in args
    # odd per-axis counts put the fitted optimum ON the grid, so the
    # sanity check (grid min == fit chi2) is exact, not discretized
    npts = 5 if quick else 17

    from pint_tpu.gls_fitter import DownhillGLSFitter
    from pint_tpu.grid import grid_chisq
    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    # -- a small correlated-noise workload (same shape as bench.py) -------
    par = """
PSR BENCHDEMO
RAJ 05:00:00 1
DECJ 15:00:00 1
F0 99.123456789 1
F1 -1.1e-14 1
PEPOCH 55500
DM 12.5 1
EFAC mjd 53000 58000 1.1
ECORR mjd 53000 58000 0.8
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 10
UNITS TDB
"""
    model = get_model(parse_parfile(par))
    base = np.linspace(55000, 56000, 40 if quick else 100)
    mjds = np.sort(np.concatenate([base, base + 0.5 / 86400.0]))
    toas = make_fake_toas_fromMJDs(mjds, model, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(7))
    f = DownhillGLSFitter(toas, model)
    chi2_fit = f.fit_toas()
    print(f"initial GLS fit: chi2 {chi2_fit:.1f} on {len(toas)} TOAs")

    # -- rule 1+2: warm with full-span corners, matched chunk -------------
    dF0 = 3 * f.errors.get("F0", 1e-10)
    dF1 = 3 * f.errors.get("F1", 1e-18)
    g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, npts)
    g1 = np.linspace(f.model.F1.value - dF1, f.model.F1.value + dF1, npts)
    chunk = npts * npts  # one-chunk executable for this grid
    t0 = time.time()
    grid_chisq(f, ("F0", "F1"), (g0[[0, -1]], g1[[0, -1]]), chunk=chunk)
    print(f"compile+warm: {time.time() - t0:.2f} s (excluded from timing)")

    t0 = time.time()
    chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), chunk=chunk)
    dt = time.time() - t0
    rate = chi2.size / dt
    print(f"grid {npts}x{npts}: {chi2.size} GLS refits in {dt:.3f} s "
          f"= {rate:.1f} fits/s")

    # -- rule 3: the throughput only counts if the physics agrees ---------
    # two-sided, like bench.py's sanity_ok: a too-LOW minimum is just as
    # broken as a too-high one, and the argmin must be the grid center
    # (the odd point counts put the fitted optimum exactly there)
    imin = np.unravel_index(np.argmin(chi2), chi2.shape)
    sane = (np.isfinite(chi2).all()
            and abs(float(chi2.min()) - chi2_fit) < 0.05 * chi2_fit
            and imin == (npts // 2, npts // 2))
    print(f"sanity: grid min {chi2.min():.1f} at {imin} vs fit chi2 "
          f"{chi2_fit:.1f} -> {'OK' if sane else 'FAILED'}")
    if not sane:
        return 1

    # -- the reference's bench_MCMC flow, reference constructor spelling --
    # (white-noise model: the chi2-likelihood MCMC path carries the same
    # no-correlated-noise restriction as the reference's, and the
    # reference benchmark's NGC6440E model is white-noise too)
    from pint_tpu import mcmc_fitter
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.sampler import EnsembleSampler

    white = get_model(parse_parfile(
        "\n".join(l for l in par.splitlines()
                  if not l.startswith(("ECORR", "TNRed")))))
    toas_w = make_fake_toas_fromMJDs(mjds, white, error_us=1.0,
                                     add_noise=True,
                                     rng=np.random.default_rng(8))
    fw = WLSFitter(toas_w, white)
    fw.fit_toas(maxiter=2)
    # the reference constructor spelling works verbatim — but passing
    # lnlike= explicitly routes sampling onto a reference-style SCALAR
    # python loop, so it is demonstrated UNtimed; bench-quality timing
    # (below) uses the default batched jax posterior, warmed first
    fm_ref = mcmc_fitter.MCMCFitter(
        toas_w, fw.model, EnsembleSampler(26), resids=True,
        lnlike=mcmc_fitter.lnlikelihood_chi2)
    mcmc_fitter.set_priors_basic(fm_ref)
    fm_ref.fit_toas(2, seed=1)
    print("reference MCMCFitter spelling (scalar path): OK")

    fm = mcmc_fitter.MCMCFitter(toas_w, fw.model, EnsembleSampler(26))
    mcmc_fitter.set_priors_basic(fm)
    fm.fit_toas(2, seed=1)  # rule 1 again: warm the batched posterior
    nsteps = 6 if quick else 20
    t0 = time.time()
    fm.fit_toas(nsteps, seed=1)
    print(f"MCMC (26 walkers x {nsteps} steps, batched, warm): "
          f"{time.time() - t0:.2f} s, acceptance "
          f"{fm.sampler.acceptance_fraction:.2f}")
    print("see bench.py + BENCH_NOTES.md for the production B1855 numbers")
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
