"""DMX: piecewise dispersion-measure variation, from binning to dmxparse.

The TPU-native analogue of the reference's
``docs/examples/example_dmx_ranges.py``: choose DMX windows from the TOA
coverage (``dmx_ranges``), attach the component, fit a time-variable DM,
and summarize with ``dmxparse``/``dmxstats`` (the NANOGrav analysis tools,
reference ``utils.py:778,1075``).

Run:  python examples/dmx_analysis.py
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.dmx import dmx_ranges, dmxparse, dmxstats
    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(PAR)
    rng = np.random.default_rng(42)
    toas = make_fake_toas_uniform(53400, 54400, 150, model, error_us=5.0,
                                  freq=(800.0, 1400.0), add_noise=True,
                                  rng=rng)

    # --- choose windows from the data -------------------------------------
    mask, dmx_comp = dmx_ranges(toas, binwidth=30.0)
    nbins = len([p for p in dmx_comp.params if p.startswith("DMX_")])
    print(f"dmx_ranges built {nbins} windows covering "
          f"{int(mask.sum())}/{len(toas)} TOAs")
    model.add_component(dmx_comp, validate=False)
    model.setup()
    # with DMX bins covering the whole span, the global DM absorbs the DMX
    # mean — freeze it, as the NANOGrav analyses do
    model.DM.frozen = True

    # --- inject a DM wander and fit it back -------------------------------
    truth = {}
    for p in sorted(model.params):
        if p.startswith("DMX_"):
            truth[p] = 2e-3 * rng.standard_normal()
            getattr(model, p).value = 0.0
            getattr(model, p).frozen = False
    import copy as _copy

    sim = _copy.deepcopy(model)
    for p, v in truth.items():
        getattr(sim, p).value = v
    toas = make_fake_toas_uniform(53400, 54400, 150, sim, error_us=2.0,
                                  freq=(800.0, 1400.0), add_noise=True,
                                  rng=np.random.default_rng(7))

    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    print(f"fit chi2 {f.resids.chi2:.1f} ({f.resids.dof} dof)")

    # --- the NANOGrav summary tools ---------------------------------------
    dx = dmxparse(f)
    rec = np.asarray(dx["dmxs"])
    tru = np.array([truth[k] for k in sorted(truth)])
    rms_in = float(np.std(tru))
    rms_out = float(np.std(rec - np.mean(rec) - (tru - np.mean(tru))))
    print(f"dmxparse: {len(rec)} bins; injected wander rms "
          f"{rms_in * 1e4:.2f}e-4, recovery residual rms "
          f"{rms_out * 1e4:.2f}e-4 pc/cm3")
    assert rms_out < 0.5 * rms_in  # the wander is really measured

    buf = io.StringIO()
    dmxstats(f.model, toas, file=buf)
    first = buf.getvalue().splitlines()[0]
    print(f"dmxstats: {first}")
    assert "DMX_" in first
    return 0


if __name__ == "__main__":
    sys.exit(main())
