"""Amortized Bayesian timing: train a normalizing flow once, serve
posteriors in milliseconds.

The VI + normalizing-flow head of arXiv 2405.08857 applied to the
repo's jitted lnposterior: build the deduped batched posterior
(:meth:`pint_tpu.bayesian.BayesianTiming.batched_posterior`), maximize
the reparameterized ELBO with the one-jitted-step Adam driver, then
register the trained flow's draw/log-prob executables on a
:class:`~pint_tpu.serving.service.TimingService` posterior door and
serve coalesced requests with zero steady-state compiles — the
interactive-latency replacement for minutes of walker evolution.

Run:  python examples/amortized_posterior.py [--quick]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """
PSR  J1234+5678
RAJ  12:34:00.0
DECJ 56:10:00.0
POSEPOCH 55000
F0   61.485476554 1
F1   -1.181e-15 1
PEPOCH 55000
DM   223.9 1
EPHEM DE440
UNITS TDB
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.amortized import (AmortizedPosterior, AmortizedVI,
                                    TrainConfig, train_flow)
    from pint_tpu.bayesian import BayesianTiming
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.serving import (PosteriorRequest, ServeConfig,
                                  TimingService)
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(io.StringIO(PAR))
    toas = make_fake_toas_uniform(54000, 55500, 60, model, freq=1400.0,
                                  error_us=2.0, add_noise=True,
                                  rng=np.random.default_rng(11))
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=3)

    # uniform priors at +-10 sigma around the fitted values — the same
    # prior surface the MCMC walkthrough samples
    prior_info = {}
    for p in ("F0", "F1", "DM"):
        par = getattr(f.model, p)
        w = 10 * float(par.uncertainty)
        prior_info[p] = {"distr": "uniform", "pmin": par.value - w,
                         "pmax": par.value + w}
    bt = BayesianTiming(f.model, toas, prior_info=prior_info)

    # the ONE typed entry point samplers and the flow head share
    bp = bt.batched_posterior()
    print(f"amortizing {bp.ndim} parameters: {bp.param_labels}")

    vi = AmortizedVI.from_bayesian(bt, n_layers=4, hidden=16, seed=1)
    steps = 60 if quick else 400
    res = train_flow(vi, TrainConfig(steps=steps, n_samples=32,
                                     lr=2e-2, seed=2))
    print(f"trained {res.steps} steps: ELBO {res.elbo_trace[0]:.1f} -> "
          f"{res.elbo_final:.1f}")
    assert res.elbo_final > res.elbo_trace[0]

    # serve it warm: draws + log-probs through the posterior door
    ap = AmortizedPosterior.from_training(vi, res)
    svc = TimingService(ServeConfig(draw_buckets=(256,)))
    svc.register_posterior(ap, seed=3)
    svc.warm_posterior([(2, 256)])
    out = svc.serve_posterior(
        [PosteriorRequest(n_draws=256, request_id=f"req-{i}")
         for i in range(2)])
    draws = np.concatenate([o.draws for o in out])
    lp = svc.serve_posterior([PosteriorRequest(points=draws[:256])])[0]
    assert np.all(np.isfinite(lp.log_probs))
    lat = svc.posterior_latency_summary()
    print(f"served {svc.posterior_served} posterior requests: "
          f"p50 {lat['p50_ms']:.1f} ms")

    # the flow posterior sits on the least-squares answer
    fitvals = np.array([float(getattr(f.model, p).value)
                        for p in bp.param_labels])
    errs = np.array([float(getattr(f.model, p).uncertainty)
                     for p in bp.param_labels])
    for i, p in enumerate(bp.param_labels):
        med = np.median(draws[:, i])
        nsig = abs(med - fitvals[i]) / errs[i]
        print(f"  {p:>4s}: {med!r} ({nsig:.2f} sigma from the WLS fit)")
        assert nsig < 5, (p, nsig)
    print("flow posterior consistent with the least-squares fit")
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
