"""Tour of the observatory registry and the clock-correction chain.

The TPU-native analogue of the reference's
``docs/examples/PINT_observatories.py`` + ``check_clock_corrections.py``:
list the registered sites, resolve aliases/tempo codes, inspect ITRF
coordinates and site velocity, walk the site->UTC->TT(BIPM) clock chain,
and register a brand-new observatory (from Python and from a JSON file).

Clock data files are absent in this image, so corrections evaluate to the
chain's zero fallback with a warning — the machinery (file discovery,
chain composition, policy) is what this demonstrates; real deployments
point $PINT_CLOCK_REPO/$TEMPO2 at a clock-file mirror.

Run:  python examples/observatories_and_clocks.py
"""

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.observatory import (Observatory, get_observatory,
                                      list_observatories, load_observatories)

    sites = list_observatories()
    print(f"{len(sites)} registered observatories, e.g. "
          f"{', '.join(sorted(sites)[:6])} ...")
    assert len(sites) >= 50

    # --- alias and code resolution ----------------------------------------
    gbt = get_observatory("gbt")
    for alias in ("GBT", "1"):  # name, tempo code
        assert get_observatory(alias).name == gbt.name
    print(f"gbt resolves from aliases {gbt.aliases!r}")

    # --- coordinates and kinematics ---------------------------------------
    x, y, z = gbt.earth_location_itrf()
    r_km = np.sqrt(x**2 + y**2 + z**2) / 1e3
    print(f"GBT ITRF |r| = {r_km:.1f} km")
    assert 6350 < r_km < 6380

    utc = np.array([55000.0])
    pv = gbt.posvel(utc, gbt.get_TDBs(utc))
    speed = float(np.linalg.norm(np.asarray(pv.vel)[:, 0]))  # km/s
    print(f"site velocity wrt SSB at MJD 55000: {speed:.1f} km/s "
          "(orbital ~29.8 + rotation)")
    assert 25 < speed < 35

    # --- the clock chain ---------------------------------------------------
    corr = gbt.clock_corrections(utc, limits="warn")
    print(f"clock corrections at MJD 55000: {float(corr[0]) * 1e6:.3f} us "
          "(zero fallback without clock files)")

    # --- registering new sites --------------------------------------------
    Observatory("my_scope", aliases=["ms"])
    assert get_observatory("ms").name == "my_scope"

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fh:
        json.dump({"lofar_x": {"itrf_xyz": [3826577.5, 461022.9, 5064892.7],
                               "aliases": ["lfx"]}}, fh)
        path = fh.name
    names = load_observatories(path)
    os.unlink(path)
    print(f"loaded {names} from JSON (reference observatories.json format)")
    lofar = get_observatory("lfx")
    assert lofar.name == "lofar_x"
    print("observatory registry round trip OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
