"""Solar-wind dispersion: the annual DM signature and fitting NE_SW.

The TPU-native analogue of the reference's ``docs/examples/solar_wind.py``:
the solar wind adds a dispersion measure that peaks each year when the
line of sight passes near the Sun.  This walkthrough shows the annual
pattern for a low-ecliptic-latitude pulsar, its strong dependence on
solar elongation, and recovery of an injected electron density NE_SW
(Edwards et al. 2006 spherical model, SWM=0; the power-law SWM=1 and
piecewise SWX variants live in the same component).

Run:  python examples/solar_wind.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    # a pulsar nearly in the ecliptic plane: strong solar-wind signature
    base = ["PSR J0030+0451\n", "ELONG 8.91\n", "ELAT 1.45\n",
            "POSEPOCH 55000\n", "F0 205.53069 1\n", "F1 -4.3e-16 1\n",
            "PEPOCH 55000\n", "DM 4.33 1\n", "UNITS TDB\n"]
    truth = 8.0  # NE_SW electron density at 1 AU [cm^-3]
    sim = get_model(base + [f"NE_SW {truth}\n"])
    clean = get_model(base + ["NE_SW 0.0\n"])

    toas = make_fake_toas_uniform(54500, 55500, 200, clean, error_us=0.5,
                                  freq=(800.0, 1400.0))
    # the solar-wind DM delay = difference between the two models
    d = np.asarray(sim.delay(toas)) - np.asarray(clean.delay(toas))
    mjds = np.asarray(toas.get_mjds(), dtype=np.float64)
    peak = mjds[np.argmax(d)]
    print(f"solar-wind delay at 800-1400 MHz: min {d.min() * 1e6:.2f} us, "
          f"max {d.max() * 1e6:.2f} us (peak at MJD {peak:.0f})")
    # two annual conjunctions inside the 1000-d span -> two delay maxima
    assert d.max() > 5 * d.min() > 0  # sharply peaked, always positive

    # --- recover the injected density --------------------------------------
    toas = make_fake_toas_uniform(54500, 55500, 200, sim, error_us=0.5,
                                  freq=(800.0, 1400.0), add_noise=True,
                                  rng=np.random.default_rng(30))
    fit = get_model(base + ["NE_SW 0.0 1\n"])
    f = DownhillWLSFitter(toas, fit)
    f.fit_toas()
    ne = f.model.NE_SW
    pull = (ne.value - truth) / ne.uncertainty
    print(f"recovered NE_SW = {ne.value:.3f} +- {ne.uncertainty:.3f} cm^-3 "
          f"({pull:+.2f} sigma from injected {truth})")
    assert abs(pull) < 4
    assert f.resids.reduced_chi2 < 1.5
    print("solar-wind density recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
