"""Photon-domain walkthrough: event TOAs, template fitting, pulse tests.

The TPU-native analogue of the reference's photon/event walkthroughs
(``docs/examples/fermi-FT1-example``, ``event_optimize`` docs): fabricate
photon arrival times from a pulse-profile template, phase-fold them with
the timing model, score significance with H-test/Z^2, and recover a spin
offset with the template-likelihood MCMC fitter (the reference fans its
walkers over an emcee process pool; here the whole half-ensemble is one
vectorized device call).

Run:  python examples/photon_events.py [--quick]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """
PSR  J0030+0451
RAJ  00:30:27.43
DECJ 04:51:39.7
POSEPOCH 55000
F0   205.53069927493
F1   -4.2977e-16
PEPOCH 55000
DM   4.333
EPHEM DE440
UNITS TDB
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.event_fitter import MCMCFitterBinnedTemplate
    from pint_tpu.eventstats import hm, z2m
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.templates.lcprimitives import LCGaussian
    from pint_tpu.templates.lctemplate import LCTemplate

    model = get_model(io.StringIO(PAR))
    nphot = 400 if quick else 1500
    toas = make_fake_toas_uniform(54990, 55010, nphot, model, error_us=1.0,
                                  obs="barycenter", freq=np.inf,
                                  rng=np.random.default_rng(30))

    # two-peak profile template; redistribute the photons to draw from it
    template = LCTemplate([LCGaussian([0.03, 0.30]), LCGaussian([0.06, 0.75])],
                          [0.35, 0.30])
    ph_now = np.asarray(model.phase(toas).frac) % 1.0
    ph_want = template.random(len(toas), rng=np.random.default_rng(31))
    dt = ((ph_want - ph_now + 0.5) % 1.0 - 0.5) / float(model.F0.value)
    toas.adjust_TOAs(dt)
    phases = np.asarray(model.phase(toas).frac) % 1.0

    h = hm(phases)
    z = z2m(phases, m=2)[-1]
    print(f"{nphot} photons: H-test = {h:.1f}, Z^2_2 = {z:.1f} "
          "(chance ~ a few for unpulsed data)")
    assert h > 50

    # perturb F0 and recover it from the photon phases alone
    truth = float(model.F0.value)
    start = get_model(io.StringIO(PAR))
    start.F0.value = truth + 2e-8
    start.F0.uncertainty = 1e-8
    start.F0.frozen = False
    f = MCMCFitterBinnedTemplate(
        toas, start, template, nwalkers=16,
        prior_info={"F0": {"distr": "uniform", "pmin": truth - 2e-7,
                           "pmax": truth + 2e-7}})
    f.fit_toas(maxiter=100 if quick else 400, seed=32)
    err = abs(float(f.model.F0.value) - truth)
    print(f"template-likelihood MCMC: F0 recovered to {err:.2e} Hz "
          f"(started 2e-08 off; posterior sigma {f.errors['F0']:.1e})")
    assert err < 1.5e-8
    print(f"acceptance fraction {f.sampler.acceptance_fraction:.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
