"""Satellite-observatory photon pipeline: orbit file -> TOAs -> phases ->
pulsation test -> template fit.

The reference's X-ray/gamma-ray workflow (``observatory/satellite_obs.py``,
``event_toas.py``, ``eventstats.py``): register a satellite observatory
from an orbit file, fold photon events through the timing model at the
spacecraft, test for pulsations (H-test / Z^2), and fit a pulse-profile
template.  The orbit here is a synthetic LEO FITS file so the walkthrough
is self-contained (the same FPorbit reader handles real NICER/NuSTAR
files).

Run:  python examples/satellite_photon_pipeline.py [--quick] [--cpu]
"""

import io
import os
import struct
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """\
PSR XRAYPSR
RAJ 5:34:31.97
DECJ 22:00:52.1
POSEPOCH 55500
F0 29.946923 1
F1 -3.77e-10 1
PEPOCH 55500
DM 56.77
TZRMJD 55500
TZRFRQ 0
TZRSITE bary
UNITS TDB
"""


def _card(key, val):
    if isinstance(val, bool):
        sval = "T" if val else "F"
        return f"{key:<8}= {sval:>20}".ljust(80).encode()
    if isinstance(val, (int, float)):
        return f"{key:<8}= {val:>20}".ljust(80).encode()
    return f"{key:<8}= '{val}'".ljust(80).encode()


def _pad(b):
    return b + b" " * ((len(b) + 2879) // 2880 * 2880 - len(b))


def _orbit_fits(path, mjds_tt, pos_km):
    """Minimal FPorbit-style FITS (TIME, X, Y, Z in meters)."""
    met = (np.asarray(mjds_tt) - 50000.0) * 86400.0
    hdr0 = b"".join([_card("SIMPLE", True), _card("BITPIX", 8),
                     _card("NAXIS", 0), b"END".ljust(80)])
    rows = b"".join(struct.pack(">dddd", t, *(p * 1e3))
                    for t, p in zip(met, pos_km))
    hdr1 = b"".join([
        _card("XTENSION", "BINTABLE"), _card("BITPIX", 8), _card("NAXIS", 2),
        _card("NAXIS1", 32), _card("NAXIS2", len(met)), _card("PCOUNT", 0),
        _card("GCOUNT", 1), _card("TFIELDS", 4),
        _card("TTYPE1", "TIME"), _card("TFORM1", "D"),
        _card("TTYPE2", "X"), _card("TFORM2", "D"),
        _card("TTYPE3", "Y"), _card("TFORM3", "D"),
        _card("TTYPE4", "Z"), _card("TFORM4", "D"),
        _card("EXTNAME", "ORBIT"), _card("MJDREFI", 50000),
        _card("MJDREFF", 0.0), _card("TIMESYS", "TT"), b"END".ljust(80),
    ])
    data = rows + b"\0" * ((len(rows) + 2879) // 2880 * 2880 - len(rows))
    with open(path, "wb") as f:
        f.write(_pad(hdr0).replace(b"\0", b" "))
        f.write(_pad(hdr1).replace(b"\0", b" "))
        f.write(data)


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.eventstats import h2sig, hm, z2m
    from pint_tpu.models import get_model
    from pint_tpu.observatory.satellite_obs import get_satellite_observatory
    from pint_tpu.templates.lcfitters import LCFitter
    from pint_tpu.templates.lcprimitives import LCGaussian
    from pint_tpu.templates.lctemplate import LCTemplate
    from pint_tpu.toa import get_TOAs_array

    # 1. register the spacecraft: circular LEO, 98-min period
    t_orb = 55499.5 + np.linspace(0, 1.5, 1500)
    w = 2 * np.pi / (98.0 / 1440.0)
    pos = 7000.0 * np.column_stack([np.cos(w * (t_orb - t_orb[0])),
                                    np.sin(w * (t_orb - t_orb[0])),
                                    np.zeros_like(t_orb)])
    with tempfile.NamedTemporaryFile(suffix=".fits", delete=False) as fh:
        orbfile = fh.name
    _orbit_fits(orbfile, t_orb, pos)
    get_satellite_observatory("DEMOSAT", orbfile, fmt="FPORBIT")
    print(f"registered DEMOSAT from {os.path.basename(orbfile)} "
          f"({len(t_orb)} orbit samples)")

    # 2. photon events at the spacecraft: draw phases from a pulse profile
    model = get_model(io.StringIO(PAR))
    nphot = 600 if quick else 2000
    rng = np.random.default_rng(17)
    truth = LCTemplate([LCGaussian([0.04, 0.3])], [0.7])
    # arrival times: uniform in time, nudged onto the profile in phase
    t_uniform = rng.uniform(55499.6, 55500.9, nphot)
    toas0 = get_TOAs_array(t_uniform, "demosat", errors=1.0, freqs=np.inf,
                           model=model)
    ph0 = np.asarray(model.phase(toas0, abs_phase=True).frac) % 1.0
    target = truth.random(nphot, rng=rng)
    F0 = float(model.F0.value)
    t_events = t_uniform + (((target - ph0 + 0.5) % 1.0) - 0.5) / F0 / 86400.0
    toas = get_TOAs_array(t_events, "demosat", errors=1.0, freqs=np.inf,
                          model=model)
    phases = np.asarray(model.phase(toas, abs_phase=True).frac) % 1.0

    # 3. pulsation tests (reference eventstats)
    h = hm(phases)
    z = z2m(phases, m=2)[-1]
    print(f"H-test = {h:.1f} ({h2sig(h):.1f} sigma), Z^2_2 = {z:.1f}")
    assert h > 50  # unmistakable pulsations

    # 4. fit the pulse-profile template to the photon phases
    fit_t = LCTemplate([LCGaussian([0.06, 0.25])], [0.5])
    f = LCFitter(fit_t, phases)
    f.fit(quiet=True)
    loc = fit_t.primitives[0].get_location()
    print(f"template fit: peak at phase {loc:.3f} (true 0.30), "
          f"width {fit_t.primitives[0].get_width():.3f} (true 0.04), "
          f"norm {fit_t.get_amplitudes()[0]:.2f} (true 0.70)")
    assert abs(loc - 0.30) < 0.02
    os.unlink(orbfile)
    print("satellite photon pipeline done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
