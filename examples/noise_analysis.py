"""Noise-model analysis: injected noise, ML recovery, epoch averaging.

The TPU-native analogue of the reference's noise-fitting walkthrough
(``docs/examples/noise-fitting-example.py``): simulate a dataset with
known EFAC/ECORR/red noise, recover the parameters by maximizing the
autodiff likelihood, then inspect epoch-averaged and whitened residuals.

Run:  python examples/noise_analysis.py [--quick]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.gls_fitter import DownhillGLSFitter
    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    with open(PAR) as fh:
        base = fh.read()
    truth = get_model(parse_parfile(
        base + "\nEFAC mjd 52000 60000 1.4 1\nECORR mjd 52000 60000 4.0 1\n"
        "TNREDAMP -12.6\nTNREDGAM 3.0\nTNREDC 8\n"))
    nepoch = 40 if quick else 120
    epochs = np.linspace(53005, 54795, nepoch)
    mjds = (epochs[:, None] + np.arange(4)[None, :] * 0.4 / 86400.0).ravel()
    toas = make_fake_toas_fromMJDs(mjds, truth, error_us=2.0, add_noise=True,
                                   add_correlated_noise=True,
                                   rng=np.random.default_rng(10))
    print(f"simulated {len(toas)} TOAs in {nepoch} ECORR epochs with "
          "EFAC=1.4, ECORR=4us, log10 red amp=-12.6")

    start = get_model(parse_parfile(
        base + "\nEFAC mjd 52000 60000 1.0 1\nECORR mjd 52000 60000 1.0 1\n"
        "TNREDAMP -13.5 1\nTNREDGAM 3.0\nTNREDC 8\n"))
    f = DownhillGLSFitter(toas, start)
    f.fit_toas(maxiter=5, noise_fit_niter=1 if quick else 2)
    for p, tv in (("EFAC1", 1.4), ("ECORR1", 4.0), ("TNREDAMP", -12.6)):
        par = getattr(f.model, p)
        print(f"  {p:>8s}: fit {par.value:8.3f} +- {par.uncertainty:.3f} "
              f"(injected {tv})")

    res = f.resids  # post-fit residuals carry the ML GP amplitudes
    avg = res.ecorr_average()
    print(f"epoch-averaged residuals: {len(avg['mjds'])} epochs, "
          f"rms {np.std(avg['time_resids']) * 1e6:.2f} us "
          f"(raw {np.std(np.asarray(res.time_resids)) * 1e6:.2f} us)")
    white = res.calc_whitened_resids()
    print(f"whitened residual std: {np.std(white):.3f} (want ~1)")
    assert 0.5 < np.std(white) < 2.0
    return 0


if __name__ == "__main__":
    sys.exit(main())
