"""Glitch analysis: inject a glitch, see it in the residuals, fit it out.

The reference's glitch workflow (``models/glitch.py``, Vela-style): simulate
TOAs from a model with a known glitch (frequency step + exponential
recovery), show the glitch signature in residuals computed WITHOUT the
glitch component, then fit GLPH/GLF0/GLF1/GLF0D and recover the injected
values.

Run:  python examples/glitch_analysis.py [--quick] [--cpu]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = """\
PSR GLITCHY
RAJ 8:35:20.6
DECJ -45:10:34.8
POSEPOCH 55500
F0 11.19 1
F1 -1.55e-11 1
PEPOCH 55500
DM 67.99
UNITS TDB
"""
GLITCH = """\
GLEP_1 55500
GLPH_1 0.0
GLF0_1 2.1e-6 1
GLF1_1 -8.0e-14 1
GLF0D_1 7.0e-7 1
GLTD_1 50
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    n = 60 if quick else 150
    truth = get_model(io.StringIO(BASE + GLITCH))
    toas = make_fake_toas_uniform(55300, 55800, n, truth, error_us=50.0,
                                  add_noise=True,
                                  rng=np.random.default_rng(11))

    # 1. the signature: without the glitch component, post-epoch residuals
    # run away quadratically (here they alias across many turns)
    no_glitch = get_model(io.StringIO(BASE))
    r0 = Residuals(toas, no_glitch, track_mode="nearest")
    mjds = np.asarray(toas.get_mjds(), float)
    pre = np.abs(np.asarray(r0.time_resids))[mjds < 55500]
    print(f"glitch-less model: pre-epoch wrms "
          f"{1e6 * pre.std():.1f} us, chi2 {r0.chi2:.0f} "
          f"(the runaway aliases across pulses)")

    # 2. fit the glitch: start from zero glitch amplitudes at the known
    # epoch (epoch search itself is a scan over GLEP, not shown)
    start = get_model(io.StringIO(
        BASE + "GLEP_1 55500\nGLPH_1 0.0 1\nGLF0_1 0.0 1\nGLF1_1 0.0 1\n"
               "GLF0D_1 0.0 1\nGLTD_1 50\n"))
    # pulse numbers from the TRUTH model keep the fit on the connected
    # track while the start model is several turns off
    toas.compute_pulse_numbers(truth)
    f = WLSFitter(toas, start, track_mode="use_pulse_numbers")
    f.fit_toas(maxiter=6)
    glf0 = float(f.model.GLF0_1.value)
    glf0d = float(f.model.GLF0D_1.value)
    glf1 = float(f.model.GLF1_1.value)
    print(f"fitted GLF0 = {glf0:.3e} Hz (true 2.1e-6), "
          f"GLF0D = {glf0d:.3e} Hz (true 7.0e-7), "
          f"GLF1 = {glf1:.2e} (true -8.0e-14)")
    assert glf0 == np.float64(glf0)
    assert abs(glf0 - 2.1e-6) < 0.3e-6
    assert abs(glf0d - 7.0e-7) < 3e-7

    r1 = f.resids
    print(f"post-fit: chi2/dof = {r1.chi2 / r1.dof:.2f}, wrms = "
          f"{1e6 * np.asarray(r1.time_resids).std():.1f} us")
    assert r1.chi2 / r1.dof < 3.0
    print("glitch analysis done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
