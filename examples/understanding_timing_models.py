"""Anatomy of a TimingModel: components, parameters, delays, design matrix.

The TPU-native analogue of the reference's
``docs/examples/understanding_timing_models.py`` walkthrough: load a model,
inspect its component pipeline and parameter surface, evaluate delay/phase,
pull the autodiff design matrix, and edit the component graph live.

Run:  python examples/understanding_timing_models.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/B1855+09_NANOGrav_9yv1.gls.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(PAR)
    print(f"model {model.PSR.value}: {len(model.components)} components")

    # --- the component pipeline -------------------------------------------
    # Delay components run in a fixed category order; each sees the partial
    # delay accumulated by the ones before it (the binary model, for
    # example, operates on barycentered times).
    print("delay pipeline: ",
          " -> ".join(type(c).__name__ for c in model.delay_components))
    print("phase pipeline: ",
          " + ".join(type(c).__name__ for c in model.phase_components))
    print("noise components:",
          ", ".join(type(c).__name__ for c in model.noise_components))

    # --- the parameter surface --------------------------------------------
    free = model.free_params
    print(f"{len(model.params)} parameters, {len(free)} free")
    f0 = model.F0
    print(f"F0 = {f0.value} {f0.units} +/- {f0.uncertainty_value} "
          f"(frozen={f0.frozen})")
    # parameters are reachable from the model or their owning component
    assert model.components["Spindown"].F0.value == model.F0.value

    # --- evaluation --------------------------------------------------------
    toas = make_fake_toas_uniform(53400, 55000, 40, model, error_us=0.5,
                                  rng=np.random.default_rng(0))
    delay = np.asarray(model.delay(toas))
    print(f"total delay over {len(toas)} TOAs: "
          f"min {delay.min():+.3f} s  max {delay.max():+.3f} s")
    phase = model.phase(toas)
    print(f"phase at first TOA: {int(phase.int_[0])} + {float(phase.frac[0]):+.6f} cycles")

    # the design matrix comes from jax.jacfwd over the phase function —
    # no hand-registered derivatives (reference timing_model.py:2174)
    M, names, units = model.designmatrix(toas)
    print(f"design matrix {M.shape[0]} x {M.shape[1]} (columns: {names[0]} + "
          f"{len(names) - 1} fitted params)")
    assert M.shape == (len(toas), len(names))

    # --- editing the component graph ---------------------------------------
    from pint_tpu.models.glitch import Glitch

    n0 = len(model.params)
    g = Glitch()
    model.add_component(g, validate=False)
    model.GLEP_1.value = 54300.0
    model.GLF0_1.value = 2e-8
    model.setup()
    d_phase = model.phase(toas)
    moved = np.abs((d_phase.int_ - phase.int_) + (d_phase.frac - phase.frac))
    print(f"added a Glitch ({len(model.params) - n0} new params); "
          f"max phase shift {moved.max():.3f} cycles")
    assert moved.max() > 0
    model.remove_component("Glitch")
    assert "Glitch" not in model.components

    # round-trip: a model is fully described by its par file
    m2 = get_model(model.as_parfile().splitlines(keepends=True))
    assert m2.F0.value == model.F0.value
    print("par-file round trip OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
