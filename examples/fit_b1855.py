"""End-to-end walkthrough: fit the NANOGrav B1855+09 9-yr dataset.

The TPU-native analogue of the reference's documentation walkthroughs
(``docs/examples/PINT_walkthrough.py``, executed as tests via the
reference's notebooks tox environment — SURVEY §4 "doc-as-test" pillar).
This script runs the full correlated-noise pipeline at real scale:

1. load the published par file (DD binary, 120+ DMX windows, per-backend
   EFAC/EQUAD/ECORR, power-law red noise);
2. build TOAs at the real tim file's epochs/frequencies/errors/flags
   (simulated: this environment ships no JPL ephemeris kernel, so real
   TOAs carry ~ms Earth-position systematics — the workload shape is
   identical);
3. fit with the downhill GLS fitter (Woodbury solves on device);
4. refit one noise parameter by maximum likelihood (autodiff gradients);
5. run a chi2 grid over the Shapiro-delay companion mass M2, returning
   the per-point refit SINI values;
6. print the fit summary.

Run:  python examples/fit_b1855.py        (add --quick for a CI-size run)
"""

import argparse
import os
import sys
import time

import numpy as np

# runnable straight from a checkout, no install needed
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/tests/datafile/B1855+09_NANOGrav_9yv1.gls.par"
TIM = "/root/reference/tests/datafile/B1855+09_NANOGrav_9yv1.tim"


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="CI-size run: fewer grid points, 1 fit iteration")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (leave the TPU lease alone)")
    args = p.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.gls_fitter import DownhillGLSFitter
    from pint_tpu.grid import grid_chisq
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromtim

    t0 = time.time()
    model = get_model(PAR)
    toas = make_fake_toas_fromtim(TIM, model, add_noise=True,
                                  rng=np.random.default_rng(1855))
    print(f"[{time.time() - t0:6.1f}s] {len(toas)} TOAs, "
          f"{len(model.free_params)} free parameters")

    f = DownhillGLSFitter(toas, model)
    chi2 = f.fit_toas(maxiter=1 if args.quick else 5)
    print(f"[{time.time() - t0:6.1f}s] GLS fit: chi2 = {chi2:.1f} "
          f"({f.resids.dof} dof, reduced {chi2 / f.resids.dof:.3f})")

    # ML noise refit of one backend's EFAC (fitter.fit_noise; pass
    # noisefit params as free in the par to fold this into fit_toas)
    f.model.EFAC1.frozen = False
    res = f.fit_noise(uncertainty=True)
    print(f"[{time.time() - t0:6.1f}s] ML noise fit: "
          + ", ".join(f"{n} = {v:.3f} +- {e:.3f}"
                      for n, v, e in zip(res.names, res.values, res.errors)))
    f.model.EFAC1.frozen = True

    npts = 4 if args.quick else 16
    dm2 = 3 * float(f.model.M2.uncertainty or 0.011)
    g_m2 = np.linspace(f.model.M2.value - dm2, f.model.M2.value + dm2, npts)
    dsini = 3 * float(f.model.SINI.uncertainty or 1.8e-4)
    g_sini = np.linspace(f.model.SINI.value - dsini,
                         min(0.999999, f.model.SINI.value + dsini), npts)
    tg = time.time()
    chi2_grid, extra = grid_chisq(f, ("M2", "SINI"), (g_m2, g_sini),
                                  niter=2, extraparnames=("F0",))
    imin = np.unravel_index(np.argmin(chi2_grid), chi2_grid.shape)
    print(f"[{time.time() - t0:6.1f}s] {npts}x{npts} M2 x SINI grid in "
          f"{time.time() - tg:.1f}s: min chi2 {float(np.min(chi2_grid)):.1f} "
          f"at M2 = {g_m2[imin[0]]:.4f}, SINI = {g_sini[imin[1]]:.6f} "
          f"(delta vs fit {float(np.min(chi2_grid)) - chi2:+.2f})")
    assert np.all(np.isfinite(chi2_grid))
    assert extra["F0"].shape == chi2_grid.shape

    print(f.get_summary().splitlines()[0])
    for line in f.get_summary().splitlines():
        if any(k in line for k in ("M2", "SINI", "F0 ", "Chisq")):
            print(line)
    print(f"[{time.time() - t0:6.1f}s] done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
