"""Polycos walkthrough: generate, write, read, and predict with polycos.

The TPU-native analogue of the reference's polyco documentation
(``polycos.py``, tempo polyco format): generate a polynomial ephemeris
for a day of observing, round-trip it through the TEMPO text format, and
check the fast phase prediction against the full timing model.

Run:  python examples/polycos_prediction.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.polycos import Polycos

    model = get_model(PAR)
    mjd_start, mjd_end = 53800.0, 53801.0
    p = Polycos.generate_polycos(model, mjd_start, mjd_end, "gbt", 60,
                                 12, 1400.0)
    print(f"generated {len(p.entries)} polyco segments "
          f"(60 min, 12 coefficients) for MJD {mjd_start}-{mjd_end}")

    with tempfile.NamedTemporaryFile("w", suffix=".dat", delete=False) as fh:
        out = fh.name
    p.write_polyco_file(out)
    p2 = Polycos.read_polyco_file(out)
    os.unlink(out)
    print(f"round-tripped through the TEMPO text format: "
          f"{len(p2.entries)} segments")

    # fast prediction vs the exact TOA pipeline at the same site epochs
    from pint_tpu.toa import TOAs

    t_check = np.linspace(mjd_start + 0.05, mjd_end - 0.05, 40)
    n = len(t_check)
    toas = TOAs(utc_mjd=np.asarray(t_check, dtype=np.longdouble),
                error_us=np.ones(n), freq_mhz=np.full(n, 1400.0),
                obs=np.array(["gbt"] * n, dtype=object),
                flags=[{} for _ in range(n)])
    toas.apply_clock_corrections(include_bipm=False)
    toas.compute_TDBs()
    toas.compute_posvels(ephem=model.EPHEM.value or "DE440")
    ph_poly = p2.eval_abs_phase(t_check)
    ph_model = model.phase(toas, abs_phase=True)
    dphase = (np.asarray(ph_poly.int_) - np.asarray(ph_model.int_)
              + np.asarray(ph_poly.frac) - np.asarray(ph_model.frac))
    # prediction coherence above the unobservable datum: the TDB
    # integration anchor fixes phase offset AND rate only up to a
    # constant+linear piece (absorbed by the PHOFF/F0 datum — see
    # tdb_integrated.py), so the meaningful residual is the detrended one.
    # tests/test_products.py checks the absolute datum at 1e-6 cycles in a
    # controlled fresh process.
    A = np.stack([np.ones_like(t_check), t_check - t_check.mean()], axis=1)
    c, *_ = np.linalg.lstsq(A, dphase, rcond=None)
    wobble = np.max(np.abs(dphase - A @ c))
    print(f"polyco vs full model: detrended prediction wobble "
          f"{wobble:.2e} cycles (datum offset {c[0]:.2e}, "
          f"rate {c[1]:.2e} cycles/day)")
    assert wobble < 1e-5
    assert abs(c[0]) < 1e-3
    spin = p2.eval_spin_freq(t_check[:3])
    print(f"predicted spin frequency: {np.asarray(spin)[0]:.9f} Hz "
          f"(F0 = {float(model.F0.value):.9f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
