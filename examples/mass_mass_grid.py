"""Simulate a relativistic binary and map the companion-mass constraint.

The TPU-native analogue of the reference's
``docs/examples/Simulate_and_make_MassMass.py``: simulate TOAs for a
Shapiro-delay binary, fit it, run a batched M2 x SINI chi2 grid (the
reference fans this over a process pool; here one compiled kernel evaluates
all points, ``pint_tpu/grid.py``), convert the grid to confidence levels,
and translate the best point into component masses with
``derived_quantities``.

Run:  python examples/mass_mass_grid.py [--quick]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.derived_quantities import (companion_mass, mass_funct,
                                             mass_funct2, pulsar_mass)
    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.grid import grid_chisq
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    # a J1614-2230-like edge-on binary: strong Shapiro signal
    par = ["PSR J0000+0000\n", "RAJ 16:14:36.5\n", "DECJ -22:30:31.2\n",
           "POSEPOCH 55000\n", "F0 317.37894 1\n", "F1 -9.7e-16 1\n",
           "PEPOCH 55000\n", "DM 34.5 1\n", "BINARY ELL1\n",
           "PB 8.6866 1\n", "A1 11.2911 1\n", "TASC 55000.0 1\n",
           "EPS1 1e-7 1\n", "EPS2 1e-7 1\n",
           "M2 0.50 1\n", "SINI 0.9995 1\n", "UNITS TDB\n"]
    model = get_model(par)
    toas = make_fake_toas_uniform(54000, 56000, 100 if quick else 300, model,
                                  error_us=0.5, add_noise=True,
                                  rng=np.random.default_rng(1614))
    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    print(f"fit: chi2 = {f.resids.chi2:.1f} ({f.resids.dof} dof); "
          f"M2 = {f.model.M2.value:.3f} +- {f.model.M2.uncertainty_value:.3f}, "
          f"SINI = {f.model.SINI.value:.5f}")

    # --- batched chi2 grid over the Shapiro pair ---------------------------
    n = 6 if quick else 16
    m2v, s2v = f.model.M2.value, f.model.SINI.value
    dm2 = 4 * f.model.M2.uncertainty_value
    dsini = 4 * f.model.SINI.uncertainty_value
    g_m2 = np.linspace(max(1e-3, m2v - dm2), m2v + dm2, n)
    g_sini = np.linspace(s2v - dsini, min(0.9999999, s2v + dsini), n)
    chi2_grid, _ = grid_chisq(f, ("M2", "SINI"), (g_m2, g_sini), niter=2)
    dchi2 = np.asarray(chi2_grid) - float(np.min(chi2_grid))
    # 2-parameter confidence levels (Wilks): 2.30 / 6.18 / 11.83
    for lvl, lab in ((2.30, "68%"), (6.18, "95%")):
        frac = float(np.mean(dchi2 < lvl))
        print(f"{lab} region covers {frac:5.1%} of the grid")
    assert np.all(np.isfinite(chi2_grid))
    imin = np.unravel_index(np.argmin(dchi2), dchi2.shape)
    m2_best, sini_best = g_m2[imin[0]], g_sini[imin[1]]
    print(f"grid minimum at M2 = {m2_best:.3f} Msun, SINI = {sini_best:.5f}")

    # --- masses from the orbit --------------------------------------------
    pb, x = f.model.PB.value, f.model.A1.value
    fm = mass_funct(pb, x)
    incl = np.degrees(np.arcsin(sini_best))
    mp = pulsar_mass(pb, x, m2_best, incl)
    print(f"mass function {fm:.6f} Msun; at the grid minimum the pulsar "
          f"mass is {mp:.2f} Msun (i = {incl:.2f} deg)")
    # consistency: mass_funct2(mp, mc, i) must reproduce the mass function
    assert abs(mass_funct2(mp, m2_best, incl) - fm) < 1e-9
    # and companion_mass inverts pulsar_mass
    mc_back = companion_mass(pb, x, incl, mp)
    assert abs(mc_back - m2_best) < 1e-6
    print(f"companion_mass inverts to {mc_back:.3f} Msun — masses consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
