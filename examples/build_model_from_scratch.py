"""Build a TimingModel programmatically — no par file required.

The TPU-native analogue of the reference's
``docs/examples/build_model_from_scratch.py``: instantiate components,
attach them to an empty TimingModel, set parameter values, then simulate
and fit as usual.  (In practice ``get_model`` also accepts a list of par
lines — shown at the end — but the component API is the point here.)

Run:  python examples/build_model_from_scratch.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import TimingModel, get_model
    from pint_tpu.models.astrometry import AstrometryEquatorial
    from pint_tpu.models.dispersion_model import DispersionDM
    from pint_tpu.models.solar_system_shapiro import SolarSystemShapiro
    from pint_tpu.models.spindown import Spindown
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    # --- assemble the component graph -------------------------------------
    model = TimingModel("J0000+0000",
                        [AstrometryEquatorial(), SolarSystemShapiro(),
                         DispersionDM(), Spindown()])
    model.PSR.value = "J0000+0000"
    model.UNITS.value = "TDB"

    model.RAJ.value = "04:37:15.9"
    model.DECJ.value = "-47:15:09.1"
    model.POSEPOCH.value = 54500.0
    model.F0.value = 173.6879489990983
    model.F1.value = -1.728e-15
    model.PEPOCH.value = 54500.0
    model.DM.value = 2.64
    for p in ("F0", "F1", "RAJ", "DECJ", "DM"):
        getattr(model, p).frozen = False

    model.setup()
    model.validate()
    print(f"built {model.PSR.value}: components "
          f"{sorted(model.components)}; {len(model.free_params)} free params")

    # --- simulate and fit --------------------------------------------------
    rng = np.random.default_rng(437)
    toas = make_fake_toas_uniform(53000, 56000, 120, model, error_us=1.0,
                                  add_noise=True, rng=rng)
    truth = {p: getattr(model, p).value for p in ("F0", "F1", "DM")}
    # perturb, then recover by fitting
    model.F0.value += 2e-10
    model.F1.value += 3e-18
    model.DM.value += 1e-4

    pre = Residuals(toas, model)
    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    print(f"prefit chi2 {pre.chi2:9.1f}  ->  postfit {f.resids.chi2:7.1f} "
          f"({f.resids.dof} dof)")
    for p in ("F0", "F1", "DM"):
        par = getattr(f.model, p)
        pull = (par.value - truth[p]) / par.uncertainty_value
        print(f"  {p:3s} recovered to {pull:+5.2f} sigma")
        assert abs(pull) < 4.0
    assert f.resids.reduced_chi2 < 1.5

    # the same model via par lines (what get_model does under the hood)
    lines = ["PSR J0000+0000\n", "RAJ 04:37:15.9\n", "DECJ -47:15:09.1\n",
             "POSEPOCH 54500\n", "F0 173.6879489990983 1\n",
             "F1 -1.728e-15 1\n", "PEPOCH 54500\n", "DM 2.64 1\n",
             "UNITS TDB\n"]
    m2 = get_model(lines)
    assert sorted(m2.components) == sorted(model.components)
    print("par-line construction matches the component-API model")
    return 0


if __name__ == "__main__":
    sys.exit(main())
