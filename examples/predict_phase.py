"""Phase-prediction walkthrough: the predict door end to end.

The serving-layer answer to "what is the apparent phase right now?":
generate a predictor cache on device (one vmapped least-squares
dispatch for every window), register it on a ``TimingService``, serve
a coalesced batch of ``PredictRequest``s through the predict door,
check the served phases against PINT's own host ``Polycos``
evaluation, and show the incremental-invalidation ledger.

Run:  python examples/predict_phase.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"

# same-scale stand-in when the reference data set is absent
FALLBACK_PAR = """PSR              PREDICT1
RAJ      17:48:52.75
DECJ    -20:21:29.0
F0       61.485476554
F1      -1.181e-15
PEPOCH   53750
DM       223.9
EPHEM    DE421
UNITS    TDB
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.polycos import Polycos
    from pint_tpu.predict import PredictorCache, PredictRequest
    from pint_tpu.serving import ServeConfig, TimingService

    if os.path.exists(PAR):
        model = get_model(PAR)
    else:
        model = get_model([ln + "\n" for ln in FALLBACK_PAR.splitlines()])
    t0 = float(model.PEPOCH.value)
    t1 = t0 + 1.0

    # one device dispatch fits every 60-min window's 12 coefficients
    cache = PredictorCache(model, t0, t1, obs="@", segLength=60.0,
                           ncoeff=12)
    print(f"predictor cache: {cache.n_windows} windows "
          f"(60 min, 12 coefficients) for MJD {t0}-{t1}")

    svc = TimingService(ServeConfig(time_buckets=(32,),
                                    batch_buckets=(1, 4)))
    svc.register_predictor(cache, warm=True)

    rng = np.random.default_rng(7)
    lo, hi = cache.coverage()
    reqs = [PredictRequest(times_mjd=np.sort(rng.uniform(lo, hi, 32)),
                           request_id=f"demo-{i}") for i in range(4)]
    out = svc.serve_predicts(reqs)
    print(f"served {len(out)} coalesced requests "
          f"(batch={out[0].batch}, bucket={out[0].bucket}, "
          f"{out[0].windows} windows touched by the first)")

    # the served numbers must match PINT's own host polyco evaluation
    host = Polycos.generate_polycos(model, t0, t1, "@", 60, 12, 1400.0)
    worst = 0.0
    for req, res in zip(reqs, out):
        hp = host.eval_abs_phase(req.times_mjd)
        dphase = (res.phase_int - np.asarray(hp.int_)
                  + res.phase_frac - np.asarray(hp.frac))
        worst = max(worst, float(np.max(np.abs(dphase))))
    print(f"device predictor vs host Polycos: max |dphase| = "
          f"{worst:.2e} cycles")
    assert worst < 1e-9

    f_served = float(out[0].freq[0])
    print(f"predicted spin frequency: {f_served:.9f} Hz "
          f"(F0 = {float(model.F0.value):.9f})")

    # incremental invalidation: only the spanned windows regenerate
    before = cache.stats()["regenerated"]
    n_inv = cache.invalidate_span(t0 + 0.20, t0 + 0.30)
    cache.predict(np.linspace(t0 + 0.21, t0 + 0.29, 8))
    regen = cache.stats()["regenerated"] - before
    print(f"invalidate_span over 0.1 d: {n_inv} windows invalidated, "
          f"{regen} regenerated lazily on the next touch "
          f"(hit rate {cache.stats()['hit_rate']:.3f})")
    # regeneration is lazy: only the invalidated windows the new
    # epochs actually LAND in repay their fit; untouched stale
    # windows wait (and never more than the span invalidated)
    assert 0 < regen <= n_inv < cache.n_windows
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
