"""Tour of the fitter family and the parameter covariance it produces.

The TPU-native analogue of the reference's
``docs/examples/understanding_fitters.py`` + ``covariance.py``: the same
dataset through WLS, downhill WLS, and downhill GLS; ``Fitter.auto``
dispatch; and the labeled parameter covariance/correlation matrices.

Run:  python examples/understanding_fitters.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import DownhillWLSFitter, Fitter, WLSFitter
    from pint_tpu.gls_fitter import DownhillGLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(PAR)
    toas = make_fake_toas_uniform(53400, 54200, 80, model, error_us=20.0,
                                  add_noise=True,
                                  rng=np.random.default_rng(3))

    # --- the one-shot and iterative WLS fitters ----------------------------
    # WLSFitter solves the linearized problem once per call; the downhill
    # variant iterates with step halving until convergence (reference
    # fitter.py:843 ModelState machinery).
    f1 = WLSFitter(toas, get_model(PAR))
    f1.fit_toas()
    f2 = DownhillWLSFitter(toas, get_model(PAR))
    f2.fit_toas()
    print(f"WLS chi2 {f1.resids.chi2:.2f}   downhill WLS chi2 "
          f"{f2.resids.chi2:.2f}")
    assert abs(f1.resids.chi2 - f2.resids.chi2) < 0.5

    # --- auto dispatch -----------------------------------------------------
    # Fitter.auto picks the fitter the model needs (reference fitter.py:193):
    # NGC6440E has no correlated noise -> downhill WLS; add ECORR -> GLS.
    fa = Fitter.auto(toas, get_model(PAR))
    print(f"Fitter.auto (white noise)      -> {type(fa).__name__}")
    assert isinstance(fa, DownhillWLSFitter)

    noisy = get_model(PAR)
    from pint_tpu.models.noise_model import EcorrNoise

    noisy.add_component(EcorrNoise(), validate=False)
    noisy.ECORR1.key = "-fake_toa"  # one epoch-correlated backend
    noisy.ECORR1.key_value = ["1"]
    noisy.ECORR1.value = 0.5
    noisy.setup()
    fg = Fitter.auto(toas, noisy)
    print(f"Fitter.auto (correlated noise) -> {type(fg).__name__}")
    assert isinstance(fg, DownhillGLSFitter)

    # --- the covariance matrix ---------------------------------------------
    cov = f2.parameter_covariance_matrix
    names = cov.get_label_names(axis=0)
    print(f"covariance matrix over {names}")
    corr = cov.to_correlation_matrix()
    i0, i1 = names.index("F0"), names.index("F1")
    print(f"corr(F0, F1) = {corr.matrix[i0, i1]:+.3f}")
    assert abs(corr.matrix[i0, i1]) <= 1.0
    # uncertainties come from the covariance diagonal
    sd = np.sqrt(cov.matrix[i0, i0])
    assert np.isclose(sd, f2.model.F0.uncertainty, rtol=1e-6)
    print(f"sqrt(diag) reproduces F0 uncertainty {sd:.3e} Hz")
    print(corr.prettyprint(prec=2).splitlines()[0])
    return 0


if __name__ == "__main__":
    sys.exit(main())
