"""Bayesian timing of wideband (TOA + DM) data.

The TPU-native analogue of the reference's
``docs/examples/bayesian-wideband-example.py``: wideband TOAs carry a DM
measurement per TOA (-pp_dm/-pp_dme flags); BayesianTiming's likelihood
stacks the TOA and DM residual axes, and the ensemble sampler draws a
posterior over spin + DM parameters.

Run:  python examples/bayesian_wideband.py [--quick]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.bayesian import BayesianTiming
    from pint_tpu.fitter import Fitter
    from pint_tpu.models import get_model
    from pint_tpu.sampler import EnsembleSampler
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(PAR)
    toas = make_fake_toas_uniform(53400, 54400, 80 if quick else 150, model,
                                  error_us=10.0, add_noise=True,
                                  wideband=True,
                                  rng=np.random.default_rng(64))
    assert toas.wideband
    print(f"{len(toas)} wideband TOAs (each carries a DM measurement)")

    # Fitter.auto dispatches to the wideband downhill fitter
    f = Fitter.auto(toas, model)
    f.fit_toas()
    print(f"wideband fit: {type(f).__name__}, chi2 = {f.resids.chi2:.1f} "
          f"({f.resids.dof} dof)")
    f.model.free_params = ["F0", "F1", "DM"]

    prior_info = {}
    for p in ("F0", "F1", "DM"):
        par = getattr(f.model, p)
        w = 20 * float(par.uncertainty)
        prior_info[p] = {"distr": "uniform", "pmin": par.value - w,
                         "pmax": par.value + w}
    bt = BayesianTiming(f.model, toas, prior_info=prior_info)
    assert bt.likelihood_method == "wb_wls"
    print(f"likelihood method: {bt.likelihood_method} "
          "(stacked TOA+DM, reference bayesian.py wideband path)")

    nwalkers, nsteps = (16, 80) if quick else (32, 400)
    s = EnsembleSampler(nwalkers, seed=4)
    s.initialize_batched(bt.lnposterior_batch, bt.nparams)
    x0 = np.array([float(getattr(f.model, p).value) for p in bt.param_labels])
    errs = np.array([float(getattr(f.model, p).uncertainty)
                     for p in bt.param_labels])
    pos = x0[None, :] + errs[None, :] \
        * np.random.default_rng(7).standard_normal((nwalkers, bt.nparams))
    s.run_mcmc(pos, nsteps)
    print(f"acceptance fraction: {s.acceptance_fraction:.2f}")

    chain = s.get_chain(flat=True, discard=nsteps // 4)
    for i, p in enumerate(bt.param_labels):
        med = float(np.median(chain[:, i]))
        nsig = abs(med - x0[i]) / errs[i]
        print(f"  {p:>4s}: median {nsig:.2f} sigma from the wideband fit")
        assert nsig < 5
    # the DM posterior must be driven by the wideband DM data: its width
    # should be comparable to the fitter's DM uncertainty
    dm_i = bt.param_labels.index("DM")
    width = float(np.std(chain[:, dm_i]))
    assert 0.2 * errs[dm_i] < width < 5 * errs[dm_i]
    print("wideband posterior consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
