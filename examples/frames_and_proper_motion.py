"""Coordinate frames and proper-motion epochs: ICRS <-> ecliptic, moving
POSEPOCH, and positions at arbitrary epochs.

The reference's frame utilities (``as_ICRS``/``as_ECL``,
``change_posepoch``, and the dummy-distance SkyCoord helpers
``utils.py:2163`` — replaced here by direct angle-space helpers
``propagate_pm``/``psr_coords_at_epoch``): convert a timing model between
equatorial and ecliptic astrometry, advance its position epoch, and
evaluate the sky position at any epoch, checking that every route agrees.

Run:  python examples/frames_and_proper_motion.py [--cpu]
"""

import io
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = """\
PSR MOVER
RAJ 10:22:58.0
DECJ 10:02:03.0
PMRA 35.0
PMDEC -48.0
PX 1.2
POSEPOCH 55000
F0 81.0 1
F1 -1e-15 1
PEPOCH 55000
DM 10.0
UNITS TDB
"""


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.utils import propagate_pm, psr_coords_at_epoch

    eq = get_model(io.StringIO(PAR))

    # 1. frame conversion round trip: delays identical in both frames
    ecl = eq.as_ECL()
    assert "AstrometryEcliptic" in ecl.components
    back = ecl.as_ICRS()
    toas = make_fake_toas_uniform(54500, 55500, 40, eq, error_us=5.0,
                                  rng=np.random.default_rng(12))
    r_eq = np.asarray(Residuals(toas, eq).time_resids)
    r_ecl = np.asarray(Residuals(toas, ecl).time_resids)
    print(f"equatorial vs ecliptic residual agreement: "
          f"{np.max(np.abs(r_eq - r_ecl)) * 1e9:.3f} ns")
    assert np.max(np.abs(r_eq - r_ecl)) < 2e-9
    assert abs(float(back.RAJ.value) - float(eq.RAJ.value)) < 1e-12

    # 2. position at an arbitrary epoch, three ways that must agree:
    #    component unit-vector path, free-function helper, PM formula
    epoch = 58650.0  # ~10 years of 59 mas/yr proper motion
    ra_m, dec_m = psr_coords_at_epoch(eq, epoch)
    a = eq.components["AstrometryEquatorial"]
    ra_c, dec_c = a.get_psr_coords(epoch)
    ra_h, dec_h = propagate_pm(*a.get_psr_coords(55000.0), 35.0, -48.0,
                               55000.0, epoch)
    sep_mas = np.hypot((ra_h - ra_c) * np.cos(dec_c), dec_h - dec_c) \
        * 180 / np.pi * 3.6e6
    print(f"coords at {epoch}: ({ra_m:.8f}, {dec_m:.8f}) rad; helper vs "
          f"component separation {sep_mas:.2e} mas")
    assert (ra_m, dec_m) == (ra_c, dec_c)
    assert sep_mas < 1e-3

    # 3. change_posepoch: RAJ/DECJ advance along the PM track, timing
    # unchanged (the model still describes the same pulsar)
    import copy

    moved = copy.deepcopy(eq)
    moved.components["AstrometryEquatorial"].change_posepoch(55500.0)
    assert float(moved.POSEPOCH.value) == 55500.0
    assert float(moved.DECJ.value) != float(eq.DECJ.value)
    r_mv = np.asarray(Residuals(toas, moved).time_resids)
    print(f"after change_posepoch(55500): residuals shift by "
          f"{np.max(np.abs(r_mv - r_eq)) * 1e9:.3f} ns (same pulsar)")
    assert np.max(np.abs(r_mv - r_eq)) < 2e-9
    print("frames and proper motion done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
