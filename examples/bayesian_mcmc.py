"""Bayesian timing: priors, batched lnposterior, ensemble MCMC.

The TPU-native analogue of the reference's ``bayesian-example`` /
``MCMC_walkthrough`` docs: set uniform priors from the fitted
uncertainties, run the jax-native affine-invariant ensemble sampler (the
whole half-ensemble evaluated as ONE vectorized device call — the
reference fans walkers over a process pool), and summarize the posterior.

Pass a ``jax.sharding.Mesh`` as ``EnsembleSampler(mesh=...)`` to shard
the walker axis over devices; chains are identical to the unsharded run.

Run:  python examples/bayesian_mcmc.py [--quick]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.bayesian import BayesianTiming
    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.sampler import EnsembleSampler
    from pint_tpu.simulation import make_fake_toas_fromtim

    model = get_model(PAR)
    toas = make_fake_toas_fromtim(TIM, model, add_noise=True,
                                  rng=np.random.default_rng(99))
    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    # sample the spin/DM subspace (astrometry stays at the fitted values)
    f.model.free_params = ["F0", "F1", "DM"]

    # uniform priors at +-20 sigma around the fitted values
    prior_info = {}
    for p in ("F0", "F1", "DM"):
        par = getattr(f.model, p)
        w = 20 * float(par.uncertainty)
        prior_info[p] = {"distr": "uniform", "pmin": par.value - w,
                         "pmax": par.value + w}
    bt = BayesianTiming(f.model, toas, prior_info=prior_info)
    print(f"sampling {bt.nparams} parameters: {bt.param_labels}")

    nwalkers, nsteps = (16, 100) if quick else (32, 600)
    s = EnsembleSampler(nwalkers, seed=2)
    s.initialize_batched(bt.lnposterior_batch, bt.nparams)
    x0 = np.array([float(getattr(f.model, p).value) for p in bt.param_labels])
    errs = np.array([float(getattr(f.model, p).uncertainty)
                     for p in bt.param_labels])
    pos = x0[None, :] + errs[None, :] * np.random.default_rng(3).standard_normal(
        (nwalkers, bt.nparams))
    s.run_mcmc(pos, nsteps)
    print(f"acceptance fraction: {s.acceptance_fraction:.2f}")

    chain = s.get_chain(flat=True, discard=nsteps // 4)
    for i, p in enumerate(bt.param_labels):
        med = np.median(chain[:, i])
        lo, hi = np.percentile(chain[:, i], [16, 84])
        nsig = abs(med - x0[i]) / errs[i]
        print(f"  {p:>4s}: {med!r} (+{hi - med:.3g} -{med - lo:.3g}), "
              f"{nsig:.2f} sigma from the WLS fit")
        assert nsig < 5, (p, nsig)
    print("posterior consistent with the least-squares fit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
