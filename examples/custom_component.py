"""How to build a timing-model component of your own.

The TPU-native analogue of the reference's
``docs/examples/How_to_build_a_timing_model_component.py``: subclass
DelayComponent, declare parameters, write the (jit-traceable) delay
function, attach it to a model, and fit its parameters — the design
matrix comes from jax.jacfwd, so NO hand-written derivatives are needed
(the reference requires a ``d_delay_d_param`` per fittable parameter).

The example component models an exponential "dip" event: a delay that
switches on at DIPEPOCH and decays with timescale DIPTAU — the shape of
the chromatic-timing events seen in J1713+0747 (kept achromatic here for
brevity).

Run:  python examples/custom_component.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DAY_S = 86400.0


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from pint_tpu.exceptions import MissingParameter
    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.models.parameter import MJDParameter, floatParameter
    from pint_tpu.models.timing_model import Component, DelayComponent
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    # --- 1. the component --------------------------------------------------
    class ExponentialDipDelay(DelayComponent):
        """delay(t) = DIPAMP * exp(-(t - DIPEPOCH)/DIPTAU) after DIPEPOCH.

        ``register = True`` puts the class in Component.component_types;
        ``delay_func`` must be pure and jit-traceable (jnp.where, not
        Python branching, for the switch-on).
        """

        register = True
        category = "exponential_dip"

        def __init__(self):
            super().__init__()
            self.add_param(MJDParameter("DIPEPOCH",
                                        description="Dip switch-on epoch"))
            self.add_param(floatParameter("DIPAMP", units="s", value=0.0,
                                          description="Dip amplitude"))
            self.add_param(floatParameter("DIPTAU", units="d", value=10.0,
                                          description="Dip decay timescale"))

        def validate(self):
            if self.DIPEPOCH.value is None:
                raise MissingParameter("ExponentialDipDelay", "DIPEPOCH")

        def delay_func(self, pv, batch, ctx, acc_delay):
            epoch = pv["DIPEPOCH"]
            epoch = epoch.to_float() if hasattr(epoch, "to_float") else epoch
            dt_d = (batch.tdb.hi - epoch) + batch.tdb.lo \
                - acc_delay / DAY_S
            dip = pv.get("DIPAMP", 0.0) * jnp.exp(-dt_d
                                                  / pv.get("DIPTAU", 1.0))
            return jnp.where(dt_d >= 0.0, dip, 0.0)

    assert "ExponentialDipDelay" in Component.component_types

    # --- 2. attach, simulate, fit -----------------------------------------
    PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
    truth_amp, truth_tau = 30e-6, 60.0

    sim = get_model(PAR)
    dip = ExponentialDipDelay()
    sim.add_component(dip, validate=False)
    sim.DIPEPOCH.value = 53700.0
    sim.DIPAMP.value = truth_amp
    sim.DIPTAU.value = truth_tau
    sim.setup()
    toas = make_fake_toas_uniform(53400, 54400, 200, sim, error_us=3.0,
                                  add_noise=True,
                                  rng=np.random.default_rng(1713))

    model = get_model(PAR)
    model.add_component(ExponentialDipDelay(), validate=False)
    model.DIPEPOCH.value = 53700.0
    model.DIPAMP.value = 1e-6  # wrong start
    model.DIPTAU.value = 40.0
    model.DIPAMP.frozen = False
    model.DIPTAU.frozen = False
    model.setup()

    pre = Residuals(toas, model)
    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    print(f"prefit chi2 {pre.chi2:8.1f} -> postfit {f.resids.chi2:6.1f} "
          f"({f.resids.dof} dof)")
    for name, truth in (("DIPAMP", truth_amp), ("DIPTAU", truth_tau)):
        par = getattr(f.model, name)
        pull = (par.value - truth) / par.uncertainty
        print(f"  {name} = {par.value:.4g} +- {par.uncertainty:.2g} "
              f"({pull:+.2f} sigma from truth)")
        assert abs(pull) < 4
    assert f.resids.reduced_chi2 < 1.5

    # the autodiff design matrix includes the new columns automatically
    M, names, units = f.model.designmatrix(toas)
    assert "DIPAMP" in names and "DIPTAU" in names
    print("custom-component columns present in the design matrix; "
          "no hand derivatives written")

    # round-trip: the component writes itself into the par file
    text = f.model.as_parfile()
    assert "DIPAMP" in text and "DIPEPOCH" in text
    print("custom component round-trips through as_parfile")
    return 0


if __name__ == "__main__":
    sys.exit(main())
