"""Wideband walkthrough: TOA+DM fitting on the NANOGrav 12.5-yr data.

The TPU-native analogue of the reference's wideband documentation
(``docs/examples/wideband-demo``): load the published B1855+09 12.5-yr
wideband dataset (every TOA carries its own DM measurement via
-pp_dm/-pp_dme flags), simulate at the real epochs (no JPL kernel in
this image), fit the stacked TOA+DM system with the downhill wideband
fitter, refit DM-noise parameters by maximum likelihood, and inspect
both residual types.

Run:  python examples/wideband_fit.py [--quick]
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/tests/datafile/B1855+09_NANOGrav_12yv3.wb.gls.par"
TIM = "/root/reference/tests/datafile/B1855+09_NANOGrav_12yv3.wb.tim"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    quick = "--quick" in args
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromtim
    from pint_tpu.wideband import WidebandDownhillFitter

    model = get_model(PAR)
    rng = np.random.default_rng(125)
    toas = make_fake_toas_fromtim(TIM, model, add_noise=True, rng=rng)
    # wideband DM measurements at the real epochs, drawn at the scaled
    # uncertainties (the tim file's -pp_dme values scaled by DMEFAC/DMEQUAD)
    dme = np.asarray(toas.get_dm_errors())
    dm_model = np.asarray(model.total_dm(toas))
    scaled = np.asarray(model.scaled_dm_uncertainty(toas))
    toas.update_dms(dm_model + rng.standard_normal(len(toas)) * scaled, dme)
    print(f"{len(toas)} wideband TOAs, {len(model.free_params)} free "
          f"parameters, median DM uncertainty {np.median(dme):.2e} pc/cm3")

    f = WidebandDownhillFitter(toas, model)
    chi2 = f.fit_toas(maxiter=1 if quick else 5)
    res = f.resids
    print(f"stacked fit: chi2 = {chi2:.1f} ({res.dof} dof, reduced "
          f"{res.reduced_chi2:.3f})")
    rms = res.rms_weighted()
    print(f"  TOA residual rms = {rms['toa'] * 1e6:.3f} us, "
          f"DM residual rms = {rms['dm']:.2e} pc/cm3")
    assert 0.8 < res.reduced_chi2 < 1.2

    # ML refit of one DM-noise parameter through the joint likelihood
    f.model.DMEFAC1.frozen = False
    r = f.fit_noise(uncertainty=True)
    i = r.names.index("DMEFAC1")
    truth = float(model.DMEFAC1.value)
    print(f"ML DM-noise fit: DMEFAC1 = {r.values[i]:.3f} +- "
          f"{r.errors[i]:.3f} (par value {truth})")
    assert abs(abs(r.values[i]) - truth) < 4 * max(r.errors[i], 0.02)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
