"""Working with TOA flags, selections, and the explicit phase offset.

The TPU-native analogue of the reference's
``docs/examples/WorkingWithFlags.py`` + ``phase_offset_example.py``:
read/write per-TOA flags, select TOA subsets by flag, tie a JUMP to a
flag-selected backend, and fit an explicit overall phase offset (PHOFF)
instead of the implicit mean subtraction.

Run:  python examples/flags_and_phase_offset.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(PAR)
    toas = make_fake_toas_uniform(53400, 54200, 60, model, error_us=10.0,
                                  add_noise=True,
                                  rng=np.random.default_rng(5))

    # --- flags are a per-TOA string dict ----------------------------------
    for i, fl in enumerate(toas.flags):
        fl["be"] = "GUPPI" if i % 2 else "PUPPI"  # fake two backends
        if i < 10:
            fl["night"] = "1"
    be, _ = toas.get_flag_value("be")
    print(f"flag 'be': {sum(v == 'PUPPI' for v in be)} PUPPI / "
          f"{sum(v == 'GUPPI' for v in be)} GUPPI TOAs")
    night, valid = toas.get_flag_value("night", as_type=int)
    print(f"flag 'night' set on {len(valid)} TOAs")

    # boolean selection by flag -> a new TOAs subset
    puppi = toas[np.array([v == "PUPPI" for v in be])]
    print(f"selected {len(puppi)} PUPPI TOAs "
          f"(MJD {float(puppi.get_mjds().min()):.0f}-"
          f"{float(puppi.get_mjds().max()):.0f})")

    # --- a JUMP tied to a flag selection ----------------------------------
    from pint_tpu.models.jump import PhaseJump
    from pint_tpu.models.parameter import maskParameter

    model.add_component(PhaseJump(), validate=False)
    model.components["PhaseJump"].add_param(
        maskParameter("JUMP", index=1, key="-be", key_value=["GUPPI"],
                      units="s", value=0.0, frozen=False), setup=True)
    model.setup()
    jumped = model.JUMP1.select_toa_mask(toas)
    print(f"JUMP1 -be GUPPI selects {len(jumped)} TOAs")
    assert len(jumped) == sum(v == "GUPPI" for v in be)

    # inject a real inter-backend offset and recover it as JUMP1
    toas.adjust_TOAs(np.where([v == "GUPPI" for v in be], 50e-6, 0.0))
    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    pull = (f.model.JUMP1.value - (-50e-6)) / f.model.JUMP1.uncertainty
    print(f"recovered JUMP1 = {f.model.JUMP1.value * 1e6:+.2f} us "
          f"({pull:+.2f} sigma from the injected -50 us)")
    assert abs(pull) < 4

    # --- explicit phase offset (PHOFF) ------------------------------------
    # Residuals normally subtract a weighted mean (an implicit offset);
    # with PhaseOffset in the model the offset is a fitted parameter
    # (reference phase_offset.py:10) and subtract_mean turns off.
    from pint_tpu.models.phase_offset import PhaseOffset

    m2 = get_model(PAR)
    m2.add_component(PhaseOffset(), validate=False)
    m2.PHOFF.value = 0.2
    m2.PHOFF.frozen = False
    m2.setup()
    # two frequencies: at a single frequency the (constant) DM column would
    # be exactly degenerate with the explicit offset
    t2 = make_fake_toas_uniform(53400, 54200, 60, get_model(PAR),
                                error_us=10.0, freq=(720.0, 1400.0),
                                add_noise=True,
                                rng=np.random.default_rng(9))
    f2 = DownhillWLSFitter(t2, m2)
    f2.fit_toas()
    print(f"fitted PHOFF = {f2.model.PHOFF.value:+.4f} +- "
          f"{f2.model.PHOFF.uncertainty:.4f} cycles")
    assert abs(f2.model.PHOFF.value) < 4 * f2.model.PHOFF.uncertainty + 0.05
    r = Residuals(t2, f2.model)
    print(f"postfit rms with explicit offset: "
          f"{r.rms_weighted() * 1e6:.2f} us")
    return 0


if __name__ == "__main__":
    sys.exit(main())
