"""Live tracking of a pulsar under load: the streaming timing engine.

Observatories emit TOAs continuously.  Refitting from scratch on every
new observing epoch rebuilds a Woodbury system that is 99% unchanged;
the streaming engine (``pint_tpu/streaming``) instead rewrites the
existing normal-equation Cholesky factor with O(k * K^2) rank-k work
per appended block and warm-starts Gauss-Newton from the previous
solution.  This walkthrough runs the whole loop at CI size:

1. **Baseline fit** — a GLS fit of the first observing campaign
   (spin + span-pinned red noise over two bands);
2. **Append** — new epoch blocks arrive through the integrity
   validate/quarantine gate and land as rank-k factor UPDATES (bad
   rows pen without touching the factor), each followed by 1-2 fused
   warm steps; parameters match a from-scratch fit of the final
   certified set to 1e-9 relative;
3. **Quarantine → downdate** — rows flagged after the fact leave the
   factor as a rank-k DOWNDATE; releasing the repaired rows is a
   rank-k UPDATE, never a rebuild;
4. **The update door** — the same operations served through
   ``TimingService.serve_updates`` with pre-warmed kernels: zero
   fresh compiles at steady state, milliseconds per update where the
   warm full-refit path costs hundreds.

Run:  python examples/streaming_update.py [--cpu]
"""

import argparse
import copy
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ap = argparse.ArgumentParser()
ap.add_argument("--cpu", action="store_true",
                help="force the CPU backend")
args = ap.parse_args()
if args.cpu:
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np  # noqa: E402

from pint_tpu import telemetry  # noqa: E402
from pint_tpu.gls_fitter import GLSFitter  # noqa: E402
from pint_tpu.models import get_model  # noqa: E402
from pint_tpu.serving import TimingService  # noqa: E402
from pint_tpu.simulation import make_fake_toas_uniform  # noqa: E402
from pint_tpu.streaming import UpdateRequest  # noqa: E402
from pint_tpu.telemetry import jaxevents  # noqa: E402

PAR = """\
PSR STREAMDEMO
RAJ 04:37:15.0
DECJ -47:15:09.0
F0 173.6879 1
F1 -1.7e-15 1
PEPOCH 55000
DM 2.64
EFAC mjd 50000 60000 1.1
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 5
TNREDTSPAN 6.0
UNITS TDB
"""

# -- the data stream: a base campaign + five later epochs -------------------
model = get_model([ln + "\n" for ln in PAR.splitlines()])
rng = np.random.default_rng(20260804)
toas = make_fake_toas_uniform(53400, 54800, 140, model,
                              freq=np.array([800.0, 1400.0]),
                              error_us=1.0, add_noise=True, rng=rng)
base, blocks = toas[np.arange(100)], [
    toas[np.arange(100 + 8 * i, 100 + 8 * (i + 1))] for i in range(5)]

# -- 1. baseline fit --------------------------------------------------------
f = GLSFitter(base, copy.deepcopy(model))
chi2 = f.fit_toas(maxiter=3)
print(f"baseline fit: {len(base)} TOAs, chi2 {chi2:.1f}")

# -- 2-4. the update door: warm kernels, stream the epochs ------------------
# basic telemetry ON: the jaxevents compile counter only counts while
# telemetry is active — the compiles=0 claim below is measured, not
# vacuous.  block_sizes covers BOTH the append shape (8) and the
# 2-row quarantine/release ops, so every dispatched rung is warm.
telemetry.activate("basic")
svc = TimingService()
svc.register_stream(f, block_sizes=[2, 8])
svc.serve_updates([UpdateRequest(new_toas=blocks[0],
                                 request_id="settle")])
before = jaxevents.counts()
for i, block in enumerate(blocks[1:4]):
    res = svc.serve_updates([UpdateRequest(new_toas=block,
                                           request_id=f"epoch-{i}")])[0]
    print(f"append epoch-{i}: +{res.outcome.block} TOAs -> chi2 "
          f"{res.chi2:.1f} in {res.latency_ms:.1f} ms "
          f"(rank-k: {res.fallback is None})")
# steady state = repeated shapes: the corrupt-block demo below
# certifies 7 of 8 rows, a fresh per-shape ingestion build outside
# the steady-state contract (the kernels stay warm either way)
steady = jaxevents.counts().compiles - before.compiles
print(f"steady-state compiles across the appends: {steady}")

# a corrupted block: the ingestion gate pens the bad row, the factor
# ingests only the certified ones — and nothing rebuilds
bad = copy.deepcopy(blocks[4])
bad.error_us[3] = -1.0
res = svc.serve_updates([UpdateRequest(new_toas=bad,
                                       request_id="corrupt")])[0]
print(f"corrupt block: {res.quarantined} row(s) quarantined at the "
      f"door, {res.outcome.block - res.quarantined} ingested")

# quarantine -> rank-k downdate; release -> rank-k update (no rebuild)
bid = res.outcome.block_id
svc.serve_updates([UpdateRequest(kind="quarantine", block_id=bid,
                                 rows=[0, 2])])
rel = svc.serve_updates([UpdateRequest(kind="release", block_id=bid,
                                       rows=[0, 2])])[0]
print(f"quarantine/release cycle: rank-k both ways, "
      f"rebuilds={svc.stream.rebuilds}")

# -- the pin: the streamed solution IS the from-scratch answer --------------
scratch = GLSFitter(svc.stream.cache.toas, copy.deepcopy(model))
scratch.fit_toas(maxiter=4)
worst = max(abs(getattr(f.model, p).value
                - getattr(scratch.model, p).value)
            / abs(getattr(scratch.model, p).value)
            for p in ("F0", "F1"))
print(f"streamed vs from-scratch fit: worst relative parameter "
      f"difference {worst:.2e}")
assert worst < 1e-9
assert steady == 0
assert svc.stream.rebuilds == 0
lat = svc.update_latency_summary()
print(f"update door: {svc.updates_served} requests, "
      f"p50 {lat['p50_ms']:.1f} ms")
telemetry.deactivate()
print("done")
sys.exit(0)
