"""Simulation walkthrough: fake TOAs, the zima CLI, random-model spread.

The TPU-native analogue of the reference's simulation docs
(``simulation.py``, the ``zima`` script): write simulated TOAs to a tim
file from the command line, read them back, fit, and visualize the
parameter-covariance spread with random model draws.

Run:  python examples/simulate_zima.py
"""

import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    out = tempfile.NamedTemporaryFile(suffix=".tim", delete=False).name
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run(
        [sys.executable, "-m", "pint_tpu.scripts.zima", PAR, out,
         "--ntoa", "80", "--startMJD", "53100", "--duration", "1500",
         # two receivers: a single-frequency dataset leaves DM degenerate
         # with the phase offset and the random-model spread blows up
         "--freq", "430", "1400",
         "--error", "2.0", "--addnoise", "--seed", "42"],
        check=True, env=env, cwd=repo)
    print(f"zima wrote {sum(1 for l in open(out) if not l.startswith('FORMAT'))} "
          "TOA lines")

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import calculate_random_models
    from pint_tpu.toa import get_TOAs

    model = get_model(PAR)
    toas = get_TOAs(out, model=model)
    os.unlink(out)
    f = DownhillWLSFitter(toas, model)
    chi2 = f.fit_toas()
    print(f"fit of the zima TOAs: reduced chi2 = {chi2 / f.resids.dof:.3f}")
    assert 0.5 < chi2 / f.resids.dof < 2.0

    # spread of models drawn from the fit covariance (plot-ready)
    dphase, rand_models = calculate_random_models(f, toas, Nmodels=30,
                                                  keep_models=True,
                                                  rng=np.random.default_rng(7))
    spread_us = np.std(np.asarray(dphase), axis=0) / float(model.F0.value) * 1e6
    print(f"random-model phase spread across {len(rand_models)} draws: "
          f"{spread_us.min():.2f}-{spread_us.max():.2f} us over the span")
    assert np.all(np.isfinite(spread_us))
    return 0


if __name__ == "__main__":
    sys.exit(main())
