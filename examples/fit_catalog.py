"""Fit a pulsar-timing ARRAY: the PTA catalog engine end to end.

Single-pulsar timing fits one par/tim pair; the real PTA workload is a
catalog of 10^2-10^3 pulsars whose noise is correlated BETWEEN pulsars
(the Hellings-Downs signature of a gravitational-wave background,
arxiv 1107.5366).  This walkthrough runs the whole pipeline at CI
size:

1. **Ingest** a ragged synthetic catalog through the integrity gate —
   corrupt rows quarantine, they never reach a fit;
2. **Bucket** the ragged ``(n_toas, n_free)`` shapes onto ladders
   learned from the catalog's own distribution (compile budget vs
   padding waste);
3. **Fit** every pulsar as ONE vmapped batched GLS program per bucket
   (padding exact by construction — parameters match dedicated
   per-pulsar fits), with warm per-bucket executables so repeat fits
   pay zero compiles;
4. **Joint likelihood**: the cross-pulsar Hellings-Downs layer — a
   block-Woodbury lnlikelihood over the common red-noise amplitude and
   spectral index, jitted and consumable by the MCMC sampler.

Run:  python examples/fit_catalog.py [--cpu] [--pulsars N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ap = argparse.ArgumentParser()
ap.add_argument("--cpu", action="store_true",
                help="force the CPU backend")
ap.add_argument("--pulsars", type=int, default=8,
                help="catalog size (default 8)")
args = ap.parse_args()
if args.cpu:
    os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import numpy as np  # noqa: E402

from pint_tpu.catalog import (  # noqa: E402
    CatalogFitter,
    JointLikelihood,
    hd_curve,
    ingest_catalog,
    make_synthetic_catalog,
)
from pint_tpu.gls_fitter import GLSFitter  # noqa: E402
from pint_tpu.serving import warm_catalog  # noqa: E402

# -- 1. ingest: the quarantine gate is the front door -----------------------
# two members carry one corrupt TOA each (zero uncertainty); the gate
# quarantines the rows and the fit never sees them
pairs = make_synthetic_catalog(n_pulsars=args.pulsars, seed=42,
                               ntoa_range=(24, 56),
                               bad_rows_in=[1, args.pulsars - 1])
report = ingest_catalog(pairs)
print(report.render())

# -- 2. + 3. bucket and fit the whole catalog as batched programs -----------
cf = CatalogFitter(report)
print(f"\nlearned ladders: ntoa={cf.bucket_plan.ntoa_ladder} "
      f"nfree={cf.bucket_plan.nfree_ladder} "
      f"-> {cf.bucket_plan.n_buckets} bucket(s), "
      f"pad waste {100 * cf.bucket_plan.pad_waste_frac:.1f}%")
warm_catalog(cf)                     # per-bucket executables, compiled once
res = cf.fit(maxiter=1)
print(f"batched fit: {res.n_pulsars} pulsars in {res.n_buckets} "
      f"program(s), {res.wall_s:.2f}s, total chi2 {res.chi2_total:.1f}")
res2 = cf.fit(maxiter=1)
print(f"repeat fit: {res2.wall_s:.2f}s, fresh compiles {res2.compiles} "
      "(warm buckets)")

# the batched result IS the dedicated result: check one member
p = report.pulsars[0]
dedicated = GLSFitter(p.toas, p.model)      # p.model stayed pristine
dedicated.fit_toas(maxiter=1)
for name in p.model.free_params:
    a = float(getattr(dedicated.model, name).value)
    b = float(getattr(p.fitted_model, name).value)
    assert abs(a - b) <= 1e-9 * max(abs(a), 1e-30), (name, a, b)
print(f"{p.name}: batched == dedicated GLSFitter on "
      f"{list(p.model.free_params)}")

# -- 4. the cross-pulsar Hellings-Downs likelihood --------------------------
print(f"\nHellings-Downs curve: hd(0+)={hd_curve(1e-6):+.3f} "
      f"hd(pi/2)={hd_curve(np.pi / 2):+.3f} hd(pi)={hd_curve(np.pi):+.3f}")
jl = JointLikelihood(cf, n_modes=3)
l0 = jl.lnlike_nocommon()
parts = jl.per_pulsar_lnlike()
assert abs(l0 - parts.sum()) <= 1e-9 * abs(parts.sum())
print(f"zero-amplitude joint lnlike {l0:.3f} == sum of per-pulsar "
      f"lnlikes {parts.sum():.3f} (factorization)")
for log10_A in (-15.0, -14.0, -13.5):
    print(f"  lnlike(log10_A={log10_A}, gamma=13/3) = "
          f"{jl.lnlike(log10_A, 13.0 / 3.0):.3f}")

# sampler consumption: the jitted batch callable drives the ensemble
from pint_tpu.sampler import EnsembleSampler  # noqa: E402

sampler = EnsembleSampler(nwalkers=8, seed=7)
sampler.initialize_batched(jl.lnlike_batch, 2)
rng = np.random.default_rng(7)
pos = np.column_stack([-14.0 + 0.3 * rng.standard_normal(8),
                       13.0 / 3.0 + 0.2 * rng.standard_normal(8)])
sampler.run_mcmc(pos, 5)
lnp = np.asarray(sampler._lnprob)
print(f"\nMCMC over (log10_A, gamma): 5 steps x 8 walkers, "
      f"acceptance {sampler.naccepted / max(sampler.ntotal, 1):.2f}, "
      f"lnpost finite: {bool(np.all(np.isfinite(lnp)))}")
print("\ncatalog walkthrough complete")
sys.exit(0)
