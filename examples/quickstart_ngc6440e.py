"""Quickstart: load a par/tim pair, fit, inspect, write results.

The TPU-native analogue of the reference's first walkthrough
(``docs/examples/PINT_walkthrough.py``): read NGC6440E, compute prefit
residuals, run the downhill WLS fitter, print the summary, and round-trip
the post-fit model through a par file.

TOAs are simulated at the real tim file's epochs (this image ships no JPL
ephemeris kernel; see examples/fit_b1855.py for the full rationale).

Run:  python examples/quickstart_ngc6440e.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if "--cpu" in args:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from pint_tpu.fitter import DownhillWLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromtim

    model = get_model(PAR)
    toas = make_fake_toas_fromtim(TIM, model, add_noise=True,
                                  rng=np.random.default_rng(6440))
    print(f"{len(toas)} TOAs spanning MJD {float(toas.get_mjds().min()):.0f}"
          f"-{float(toas.get_mjds().max()):.0f}, "
          f"{len(model.free_params)} free parameters")

    prefit = Residuals(toas, model)
    print(f"prefit  rms = {prefit.rms_weighted() * 1e6:8.3f} us, "
          f"chi2 = {prefit.chi2:.1f}")

    f = DownhillWLSFitter(toas, model)
    f.fit_toas()
    post = f.resids
    print(f"postfit rms = {post.rms_weighted() * 1e6:8.3f} us, "
          f"chi2 = {post.chi2:.1f} ({post.dof} dof, "
          f"reduced {post.reduced_chi2:.3f})")
    print(f.get_summary())

    # round-trip the fitted model through par text
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".par",
                                     delete=False) as fh:
        fh.write(f.model.as_parfile())
        out = fh.name
    m2 = get_model(out)
    os.unlink(out)
    assert m2.F0.value == f.model.F0.value
    print("post-fit par round-trips losslessly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
